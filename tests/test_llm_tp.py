"""Mesh-sharded serving (ISSUE 17): a tp-sharded SlotEngine must
produce bit-for-bit the single-device token stream — params sharded by
their logical axes, the paged KV pool sharded on its KV-heads axis,
cache donation surviving under sharding — plus the decode roofline
profiler's hardening (zero-bandwidth guard, window-reset API)."""

import os
import sys

import jax
import numpy as np
import pytest

from ray_tpu.llm.engine import SlotEngine
from ray_tpu.models import llama
from ray_tpu.parallel.mesh import MeshSpec

CFG = llama.CONFIGS["llama-tiny"]
PS = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_two = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 (virtual) devices")


@pytest.fixture(scope="module")
def params():
    p, _ = llama.init_params(jax.random.PRNGKey(0), CFG)
    return p


def _drive(eng, prompt, max_new, **kw):
    h = eng.submit(prompt, max_new=max_new, **kw)
    for _ in range(4000):
        if h._done.is_set():
            return h.result(timeout=0).tokens
        eng.step()
    raise AssertionError("engine did not finish")


@needs_two
def test_tp2_token_parity_params_and_pages_sharded(params):
    """The acceptance criterion: tp1-vs-tp2 bit-for-bit token parity
    with params AND KV pages actually sharded (verified via sharding
    specs, not just absence of errors)."""
    rng = np.random.default_rng(5)
    prompt = [int(t) for t in rng.integers(1, CFG.vocab_size, size=21)]
    eng1 = SlotEngine(params, CFG, num_slots=2, chunk=8, page_size=PS,
                      decode_block=2)
    mesh = MeshSpec(tp=2).build(jax.devices()[:2])
    eng2 = SlotEngine(params, CFG, num_slots=2, chunk=8, page_size=PS,
                      decode_block=2, mesh=mesh)
    # Placement must actually shard: qkv + mlp params over tp, and the
    # page pool's KV-heads axis over tp — not silently replicate.
    assert "tp" in str(eng2._params["blocks"]["wq"].sharding.spec)
    assert "tp" in str(eng2._params["blocks"]["w_gate"].sharding.spec)
    assert tuple(eng2._cache["kv"].sharding.spec) == \
        (None, None, None, None, "tp")
    # Greedy parity.
    assert _drive(eng2, prompt, 16) == _drive(eng1, prompt, 16)
    # Seeded sampling parity: the per-request fold_in stream makes the
    # draw independent of the mesh, so sampled outputs match too.
    s1 = _drive(eng1, prompt, 16, temperature=0.7, seed=99)
    assert _drive(eng2, prompt, 16, temperature=0.7, seed=99) == s1
    # Donation under sharding: after full requests (many donated
    # steps), the cache must STILL carry the tp sharding — a silent
    # reshard-to-replicated would defeat the whole point.
    assert tuple(eng2._cache["kv"].sharding.spec) == \
        (None, None, None, None, "tp")


@needs_two
def test_tp_must_divide_head_counts(params):
    """A mesh whose tp size doesn't divide the KV-head count must be
    rejected at construction, not fail inside a compiled program."""
    mesh = MeshSpec(tp=2).build(jax.devices()[:2])
    bad = llama.LlamaConfig(vocab_size=512, max_seq=128, num_layers=1,
                            num_heads=3, num_kv_heads=3, d_model=48,
                            d_mlp=96, dtype=None)
    p, _ = llama.init_params(jax.random.PRNGKey(0), bad)
    with pytest.raises(ValueError, match="tp=2 must divide"):
        SlotEngine(p, bad, num_slots=2, chunk=8, page_size=8, mesh=mesh)


def test_decode_profile_guard_and_reset(params):
    """Satellite hardening: hbm_bandwidth_gbps <= 0 must degrade to
    roofline_frac 0.0 (not ZeroDivisionError), and reset_decode_profile
    must zero the window so bench stages measure independently."""
    from ray_tpu.core.config import config

    eng = SlotEngine(params, CFG, num_slots=2, chunk=8, page_size=PS,
                     decode_block=2)
    eng.warmup()
    handles = [eng.submit([1, 2, 3, 4, 5], max_new=12) for _ in range(2)]
    for _ in range(4000):
        if all(h._done.is_set() for h in handles):
            break
        eng.step()
    prof = eng.decode_profile()
    assert prof["steps"] > 0 and prof["roofline_frac"] > 0
    assert prof["devices"] == 1
    cfg_obj = config()
    old = cfg_obj.hbm_bandwidth_gbps
    try:
        cfg_obj.apply_overrides({"hbm_bandwidth_gbps": 0.0})
        guarded = eng.decode_profile()  # must not raise
        assert guarded["roofline_frac"] == 0.0
        assert guarded["steps"] == prof["steps"]
    finally:
        cfg_obj.apply_overrides({"hbm_bandwidth_gbps": old})
    eng.reset_decode_profile()
    zeroed = eng.decode_profile()
    assert zeroed["steps"] == 0 and zeroed["roofline_frac"] == 0.0


@pytest.mark.slow
@needs_two
def test_multichip_serving_dryrun_stage():
    """The multichip dryrun's serving stage end-to-end (slow: compiles
    the engine twice). The dryrun prints the parity line that lands in
    the MULTICHIP_*.json stdout tail."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as g

    g._dryrun_llm_serving_tp(jax.devices())
