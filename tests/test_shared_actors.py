"""Shared-process ("lightweight") actors: many multiplexed instances
per host worker — the many-actors scalability envelope on one box
(reference scale test: release/benchmarks/distributed/test_many_actors.py,
which needs a multi-node cluster for process count alone)."""

import time

import pytest


@pytest.fixture()
def rt4():
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=4)
    yield rt
    rt.shutdown()


def test_shared_actor_basic(rt4):
    rt = rt4

    @rt.remote(shared_process=True)
    class Counter:
        def __init__(self, start):
            self.n = start

        def add(self, k):
            self.n += k
            return self.n

        def pid(self):
            import os

            return os.getpid()

    a = Counter.remote(10)
    b = Counter.remote(100)
    assert rt.get(a.add.remote(1), timeout=60) == 11
    assert rt.get(b.add.remote(1), timeout=60) == 101
    assert rt.get(a.add.remote(2), timeout=30) == 13
    # state is isolated even when co-hosted
    assert rt.get(b.add.remote(2), timeout=30) == 103


def test_shared_actors_multiplex_few_processes(rt4):
    rt = rt4

    @rt.remote(shared_process=True)
    class P:
        def pid(self):
            import os

            return os.getpid()

    actors = [P.remote() for _ in range(24)]
    pids = set(rt.get([a.pid.remote() for a in actors], timeout=120))
    # 24 actors share at most MAX_SHARED_HOSTS processes
    assert len(pids) <= 4, f"expected <=4 host processes, got {len(pids)}"


def test_shared_actor_terminate_keeps_host_alive(rt4):
    rt = rt4

    @rt.remote(shared_process=True)
    class A:
        def pid(self):
            import os

            return os.getpid()

    a = A.remote()
    b = A.remote()
    pid_a = rt.get(a.pid.remote(), timeout=60)
    pid_b = rt.get(b.pid.remote(), timeout=60)
    rt.kill(a)
    time.sleep(0.3)
    # killing a must not kill b's host (even when co-hosted)
    assert rt.get(b.pid.remote(), timeout=30) == pid_b
    with pytest.raises(Exception):
        rt.get(a.pid.remote(), timeout=30)
    del pid_a


def test_shared_actor_restart_on_host_death(rt4):
    rt = rt4

    @rt.remote(shared_process=True, max_restarts=2)
    class R:
        def __init__(self):
            self.calls = 0

        def bump(self):
            self.calls += 1
            return self.calls

        def die(self):
            import os

            os._exit(1)

    a = R.remote()
    b = R.remote()
    assert rt.get(a.bump.remote(), timeout=60) == 1
    assert rt.get(b.bump.remote(), timeout=60) == 1
    # crash the shared host: BOTH actors must restart (state reset)
    try:
        rt.get(a.die.remote(), timeout=30)
    except Exception:
        pass
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            assert rt.get(a.bump.remote(), timeout=30) >= 1
            assert rt.get(b.bump.remote(), timeout=30) >= 1
            ok = True
            break
        except Exception:
            time.sleep(0.5)
    assert ok, "actors did not restart after shared host death"


def test_shared_actor_async_method_finishes_on_terminate(rt4):
    """Eviction must not strand an in-flight async method: the actor's
    loop stops only after pending coroutines complete."""
    import gc

    rt = rt4

    @rt.remote(shared_process=True)
    class A:
        async def slow(self):
            import asyncio

            await asyncio.sleep(0.5)
            return "done"

    a = A.remote()
    ref = a.slow.remote()
    del a  # handle out of scope -> terminate while slow() is in flight
    gc.collect()
    assert rt.get(ref, timeout=60) == "done"


def test_shared_actor_on_daemon_node_degrades_to_dedicated():
    """On a daemon-process node (pool in another OS process) shared
    actors fall back to dedicated workers — create/call/kill must all
    behave normally."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    if rt.is_initialized():
        rt.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        nid = cluster.add_node(num_cpus=1, remote=True)
        cluster.wait_for_nodes(timeout=120)

        @rt.remote(shared_process=True)
        class D:
            def where(self):
                import os

                return os.getpid()

        a = D.options(
            scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
                node_id=nid.binary(), soft=False)).remote()
        pid = rt.get(a.where.remote(), timeout=120)
        assert isinstance(pid, int)
        rt.kill(a)
        with pytest.raises(Exception):
            rt.get(a.where.remote(), timeout=30)
    finally:
        cluster.shutdown()


def test_shared_actor_creation_throughput(rt4):
    """The envelope claim in miniature: shared actors create orders of
    magnitude faster than process-per-actor (no spawn, no jax import)."""
    rt = rt4

    @rt.remote(shared_process=True)
    class S:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [S.remote() for _ in range(100)]
    assert sum(rt.get([a.ping.remote() for a in actors],
                      timeout=180)) == 100
    dt = time.perf_counter() - t0
    # process-per-actor costs ~3s each on this box; shared must be far
    # under 1s per actor even with compile noise
    assert dt < 60, f"100 shared actors took {dt:.1f}s"
