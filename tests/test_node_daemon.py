"""Daemon-backed nodes: separate OS processes over loopback TCP.

VERDICT round-1 item 1 criteria: two daemons as real processes (no shared
Python state), tasks/actors/objects/PGs/chaos across them, and a
large object produced on host A gettable from host B via the network
transfer path (forced with RT_FORCE_OBJECT_TRANSFER).
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster, NodeKiller


@pytest.fixture
def daemon_cluster():
    cluster = Cluster(head_node_args={
        "num_cpus": 2,
        "env": {"RT_FORCE_OBJECT_TRANSFER": "1"},
    })
    ids = [
        cluster.add_node(num_cpus=2, resources={"zone_a": 1.0}, remote=True),
        cluster.add_node(num_cpus=2, resources={"zone_b": 1.0}, remote=True),
    ]
    cluster.wait_for_nodes()
    yield cluster, ids
    cluster.shutdown()


def test_daemons_are_separate_processes(daemon_cluster):
    cluster, (n1, n2) = daemon_cluster
    import os

    node1 = cluster.runtime.scheduler.get_node(n1)
    node2 = cluster.runtime.scheduler.get_node(n2)
    assert node1.is_remote and node2.is_remote
    pids = {node1.process.pid, node2.process.pid}
    assert os.getpid() not in pids and len(pids) == 2
    for pid in pids:
        os.kill(pid, 0)  # raises if not actually running


def test_tasks_actors_across_daemons(daemon_cluster):
    cluster, _ = daemon_cluster

    @rt.remote(resources={"zone_a": 0.1})
    def square(x):
        return x * x

    assert rt.get([square.remote(i) for i in range(8)]) == [
        i * i for i in range(8)]

    @rt.remote(resources={"zone_b": 0.1})
    class Counter:
        def __init__(self):
            self.x = 0

        def add(self, k):
            self.x += k
            return self.x

    c = Counter.remote()
    assert rt.get([c.add.remote(2) for _ in range(5)])[-1] == 10


def test_cross_daemon_object_transfer(daemon_cluster):
    """>max_direct_call object produced on daemon A, consumed on daemon B.
    RT_FORCE_OBJECT_TRANSFER makes workers treat other nodes' arenas as
    unattachable (real multi-host), forcing the chunked TCP pull."""
    cluster, _ = daemon_cluster

    @rt.remote(resources={"zone_a": 0.1})
    def produce(n):
        return np.arange(n, dtype=np.int32)

    @rt.remote(resources={"zone_b": 0.1})
    def consume(arr):
        return int(arr.sum())

    n = 3 * 1024 * 1024 // 4  # ~3MB, multiple chunks at play driver-side
    ref = produce.remote(n)
    assert rt.get(consume.remote(ref)) == n * (n - 1) // 2
    # the driver itself can pull it too (head-side network path)
    assert len(rt.get(ref)) == n


def test_placement_group_across_daemons(daemon_cluster):
    cluster, _ = daemon_cluster
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=30)
    nodes = {nid.hex() for nid in pg.bundle_nodes}
    assert len(nodes) == 2
    rt.remove_placement_group(pg)


@pytest.mark.chaos
def test_daemon_chaos_sigkill_retries():
    """SIGKILL one daemon mid-workload: driver sees EOF, fails the node,
    and retries/reconstructs so the workload still completes."""
    cluster = Cluster(head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=2, remote=True)
        cluster.add_node(num_cpus=2, remote=True)
        cluster.wait_for_nodes()

        @rt.remote(max_retries=4)
        def slow(i):
            time.sleep(0.3)
            return i

        refs = [slow.remote(i) for i in range(16)]
        killer = NodeKiller(cluster, max_kills=1)
        time.sleep(0.5)
        killed = killer.kill_one()
        assert killed is not None
        results = rt.get(refs, timeout=120)
        assert sorted(results) == list(range(16))
    finally:
        cluster.shutdown()


def test_cross_daemon_transfer_is_peer_to_peer(daemon_cluster):
    """Worker-to-worker object pulls must go daemon->daemon through the
    holder's ObjectServer (PullManager), NOT relay through the head —
    the head relay counter stays cold (reference: pull_manager.h:47,
    push_manager.h:29 — raylets transfer directly)."""
    cluster, _ = daemon_cluster
    rtime = cluster.runtime
    base = getattr(rtime, "relay_fetch_count", 0)

    @rt.remote(resources={"zone_a": 0.1})
    def produce(n):
        return np.arange(n, dtype=np.int32)

    @rt.remote(resources={"zone_b": 0.1})
    def consume(arr):
        return int(arr.sum())

    n = 2 * 1024 * 1024 // 4
    total = 0
    refs = [produce.remote(n) for _ in range(3)]
    total = rt.get([consume.remote(r) for r in refs], timeout=120)
    assert total == [n * (n - 1) // 2] * 3
    assert getattr(rtime, "relay_fetch_count", 0) == base, (
        "cross-daemon pull used the head relay instead of P2P")


def test_holder_daemon_killed_mid_pull_recovers_via_lineage():
    """SIGKILL the daemon HOLDING an object while a consumer on another
    daemon pulls it: the pull fails, the object is LOST, and lineage
    reconstruction re-runs the producer so the consumer still finishes."""
    import os
    import signal

    cluster = Cluster(head_node_args={
        "num_cpus": 2,
        "env": {"RT_FORCE_OBJECT_TRANSFER": "1"},
    })
    try:
        holder = cluster.add_node(num_cpus=2, resources={"hold": 1.0},
                                  remote=True)
        cluster.add_node(num_cpus=2, resources={"use": 1.0}, remote=True)
        cluster.wait_for_nodes()

        @rt.remote(resources={"hold": 0.1}, max_retries=4)
        def produce(n):
            return np.ones(n, dtype=np.int64)

        @rt.remote(resources={"use": 0.1}, max_retries=4)
        def consume(arr):
            return int(arr.sum())

        n = 4 * 1024 * 1024 // 8
        ref = produce.remote(n)
        rt.wait([ref], num_returns=1, timeout=60)  # sealed on holder
        out_ref = consume.remote(ref)
        # Kill the holder while the consumer's pull is (likely) in flight.
        holder_node = cluster.runtime.scheduler.get_node(holder)
        os.kill(holder_node.process.pid, signal.SIGKILL)
        # A replacement host joins (elastic recovery); lineage re-runs
        # produce there and the consumer's pull completes.
        cluster.add_node(num_cpus=2, resources={"hold": 1.0}, remote=True)
        assert rt.get(out_ref, timeout=180) == n
    finally:
        cluster.shutdown()
