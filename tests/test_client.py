"""Ray-Client-equivalent tests: remote driver over the socket proxy.

Reference coverage analog: python/ray/tests/test_client.py — tasks,
actors, put/get/wait, ref passing, error propagation through the proxy.
"""

import pytest


@pytest.fixture(scope="module")
def client(rt_shared_module):
    from ray_tpu.client import ClientServer, connect

    server = ClientServer()
    server.start()
    session = connect(server.address)
    yield session
    session.close()
    server.stop()


@pytest.fixture(scope="module")
def rt_shared_module():
    import ray_tpu as rt

    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    rt.shutdown()


def test_put_get_roundtrip(client):
    ref = client.put({"a": [1, 2, 3]})
    assert client.get(ref) == {"a": [1, 2, 3]}


def test_remote_function(client):
    @client.remote
    def add(a, b):
        return a + b

    assert client.get(add.remote(2, 40)) == 42


def test_ref_passing_between_tasks(client):
    @client.remote
    def double(x):
        return x * 2

    r1 = double.remote(21)
    r2 = double.remote(r1)  # client ref as arg resolves server-side
    assert client.get(r2) == 84


def test_wait(client):
    import time

    @client.remote
    def fast():
        return 1

    @client.remote
    def slow():
        time.sleep(5)
        return 2

    refs = [slow.remote(), fast.remote()]
    ready, pending = client.wait(refs, num_returns=1, timeout=4)
    assert len(ready) == 1 and len(pending) == 1
    assert client.get(ready[0]) == 1


def test_actor_lifecycle(client):
    @client.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert client.get(c.incr.remote()) == 11
    assert client.get(c.incr.remote(by=5)) == 16
    client.kill(c)


def test_error_propagates(client):
    @client.remote
    def boom():
        raise ValueError("kapow")

    ref = boom.remote()
    with pytest.raises(Exception, match="kapow"):
        client.get(ref)


def test_cluster_info(client):
    info = client.cluster_info()
    assert info["nodes"] >= 1
    assert info["resources"].get("CPU", 0) > 0


def test_two_sessions_isolated(client, rt_shared_module):
    from ray_tpu.client import ClientServer, connect

    server2 = ClientServer()
    server2.start()
    s2 = connect(server2.address)
    try:
        ref = s2.put("second-session")
        assert s2.get(ref) == "second-session"
        # The first session can't see the second's refs.
        from ray_tpu.client.client import ClientObjectRef

        foreign = ClientObjectRef(ref.hex(), client)
        with pytest.raises(Exception):
            client.get(foreign, timeout=2)
    finally:
        s2.close()
        server2.stop()


def test_remote_with_options(client):
    @client.remote(num_cpus=1, max_retries=2)
    def opt_task():
        return "opted"

    assert client.get(opt_task.remote()) == "opted"
