"""Tests: runtime_env, multiprocessing Pool, ParallelIterator, job submission."""

import time

import pytest


def test_runtime_env_env_vars(rt_shared):
    import ray_tpu as rt

    @rt.remote(runtime_env={"env_vars": {"RT_TEST_VAR": "hello"}})
    def read():
        import os

        return os.environ.get("RT_TEST_VAR")

    assert rt.get(read.remote(), timeout=30) == "hello"


def test_runtime_env_working_dir(rt_shared, tmp_path):
    import ray_tpu as rt
    from ray_tpu.runtime_env import RuntimeEnv

    (tmp_path / "side_mod_abc.py").write_text("X = 'from-working-dir'\n")

    @rt.remote(runtime_env=RuntimeEnv(working_dir=str(tmp_path)))
    def use():
        import side_mod_abc

        return side_mod_abc.X

    assert rt.get(use.remote(), timeout=30) == "from-working-dir"


def test_runtime_env_validation():
    from ray_tpu.runtime_env import RuntimeEnv

    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"a": 1})


def test_mp_pool_map(rt_shared):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        assert pool.map(lambda x: x * x, range(10)) == [
            i * i for i in range(10)
        ]


def test_mp_pool_starmap_apply(rt_shared):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(lambda x: x + 1, (41,)) == 42


def test_mp_pool_imap_unordered(rt_shared):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        out = sorted(pool.imap_unordered(lambda x: x * 2, range(8)))
    assert out == [i * 2 for i in range(8)]


def test_parallel_iterator(rt_shared):
    from ray_tpu.util.iter import ParallelIterator

    it = ParallelIterator.from_items(list(range(20)), num_shards=2)
    out = sorted(it.for_each(lambda x: x * 10).gather_sync())
    assert out == [i * 10 for i in range(20)]
    it.stop()


def test_parallel_iterator_filter_batch(rt_shared):
    from ray_tpu.util.iter import ParallelIterator

    it = ParallelIterator.from_items(list(range(10)), num_shards=2)
    batches = list(it.filter(lambda x: x % 2 == 0).batch(2).gather_sync())
    flat = sorted(x for b in batches for x in b)
    assert flat == [0, 2, 4, 6, 8]
    it.stop()


def test_job_manager_lifecycle(tmp_path):
    from ray_tpu.job_submission import JobManager, JobStatus

    mgr = JobManager(log_dir=str(tmp_path))
    sid = mgr.submit("echo job-output-123 && exit 0")
    assert mgr.wait(sid, timeout=30) == JobStatus.SUCCEEDED
    assert "job-output-123" in mgr.logs(sid)

    sid2 = mgr.submit("exit 3")
    assert mgr.wait(sid2, timeout=30) == JobStatus.FAILED
    assert mgr.details(sid2).returncode == 3


def test_job_http_roundtrip(tmp_path):
    from ray_tpu.job_submission import (
        JobManager,
        JobServer,
        JobSubmissionClient,
    )

    server = JobServer(JobManager(log_dir=str(tmp_path)), port=18268).start()
    try:
        client = JobSubmissionClient("http://127.0.0.1:18268")
        sid = client.submit_job(entrypoint="echo from-http")
        deadline = time.time() + 30
        while time.time() < deadline:
            if client.get_job_status(sid) in ("SUCCEEDED", "FAILED"):
                break
            time.sleep(0.1)
        assert client.get_job_status(sid) == "SUCCEEDED"
        assert "from-http" in client.get_job_logs(sid)
        assert any(j["submission_id"] == sid for j in client.list_jobs())
    finally:
        server.stop()
