"""Paged KV cache + radix prefix reuse (ISSUE 15): token parity of the
paged engine vs the dense reference paths, bit-for-bit prefix-hit
outputs (greedy AND seeded sampling), COW fork isolation, page
accounting (no leaks, reserved scratch page), LRU eviction under pool
pressure, and bounded-admission shedding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.engine import SlotEngine
from ray_tpu.llm.paged import OverloadedError, PagePool, RadixIndex
from ray_tpu.models import llama

CFG = llama.CONFIGS["llama-tiny"]
PS = 8  # page size under test: 16 pages per 128-token sequence


@pytest.fixture(scope="module")
def params():
    p, _ = llama.init_params(jax.random.PRNGKey(0), CFG)
    return p


@pytest.fixture(scope="module")
def engine(params):
    """Shared prefix-caching engine (compiles once for the module)."""
    return SlotEngine(params, CFG, num_slots=3, chunk=8, page_size=PS)


def reference_tokens(params, prompt, max_new):
    out = llama.generate(params, np.asarray([prompt], dtype=np.int32),
                         CFG, max_new=max_new)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def drain(engine, handles, max_steps=800):
    for _ in range(max_steps):
        if all(h._done.is_set() for h in handles):
            return
        engine.step()
    raise AssertionError("engine did not finish in max_steps")


def run_one(engine, prompt, max_new=8, **kw):
    h = engine.submit(prompt, max_new=max_new, **kw)
    drain(engine, [h])
    return h.result(timeout=0).tokens


# -- pool / radix units -------------------------------------------------------

def test_page_pool_refcounts_and_lru():
    pool = PagePool(6)  # scratch + 5
    assert pool.free_count == 5 and pool.used_count == 1
    a, b = pool.alloc(), pool.alloc()
    assert 0 not in (a, b), "scratch page must never be allocated"
    pool.ref(a)
    assert not pool.unref(a)  # still borrowed
    assert pool.unref(a) and pool.free_count == 4
    assert pool.unref(b) and pool.free_count == 5
    # LRU: freed pages re-issue oldest-first, after the untouched tail.
    order = [pool.alloc() for _ in range(5)]
    assert order[-2:] == [a, b]
    assert pool.used_count + pool.free_count == pool.num_pages


def test_radix_match_insert_evict():
    pool = PagePool(8)
    idx = RadixIndex(pool, 4)
    prompt = list(range(1, 11))  # 10 tokens -> 2 full pages of 4
    pages = [pool.alloc(), pool.alloc()]
    assert idx.insert(prompt, pages) == 2
    full, partial = idx.match(prompt)
    assert full == pages and partial is None  # 2 tokens left < 1 chunk
    # Extending prompt: same 2 full pages match, no partial beyond.
    full, partial = idx.match(prompt + [99, 98, 97])
    assert full == pages
    # Diverging inside the second chunk: 1 full page + partial tokens.
    full, partial = idx.match(prompt[:6] + [55, 44, 33, 22])
    assert full == pages[:1]
    assert partial == (pages[1], 2)  # tokens 5,6 shared inside page 2
    # Release the inserter's refs: index alone holds the pages now.
    for p in pages:
        pool.unref(p)
    # Eviction is leaf-first: one page frees from the deepest node.
    assert idx.evict(1) == 1
    full, _ = idx.match(prompt)
    assert full == pages[:1]
    assert idx.clear() == 1
    assert pool.used_count == 1  # only scratch


# -- kernel parity: paged vs dense programs -----------------------------------

def test_paged_cache_layout_heads_minor():
    """The page pool is ONE fused array [L, 2, pages, page_size, Hkv,
    hd] — K and V stacked so the decode gather is a single HBM sweep,
    heads-minor so a gathered page reshapes to the seq-major attention
    view without a materializing transpose, and axis 4 carries the
    'kv' logical axis for tp sharding."""
    cache = llama.init_paged_kv_cache(CFG, 7, PS)
    assert set(cache) == {"kv"}
    assert cache["kv"].shape == (CFG.num_layers, 2, 7, PS,
                                 CFG.num_kv_heads, CFG.head_dim)
    # The logical-axis annotation must line up with that shape: exactly
    # one 'kv' entry, on the heads axis.
    assert llama.PAGED_KV_AXES == (None, None, None, None, "kv", None)


def test_paged_sampled_parity_vs_dense_reference(engine, params):
    """Seeded sampling through the paged engine must reproduce a dense
    decode_step loop drawing from the same per-request fold_in stream —
    pins both the kernel numerics (heads-minor layout) and the sampling
    position bookkeeping (token j drawn at qpos = prompt_len + j)."""
    rng = np.random.default_rng(67)
    prompt = [int(t) for t in rng.integers(1, CFG.vocab_size, size=18)]
    temperature, seed, max_new = 0.8, 4242, 10
    # Dense reference: single-row KV cache, one decode_step per token.
    cache = llama.init_kv_cache(CFG, 1)
    logits = None
    for i, t in enumerate(prompt):
        logits, cache = llama.decode_step(
            params, cache, jnp.asarray([t], jnp.int32),
            jnp.asarray(i, jnp.int32), CFG)
    ref = []
    for j in range(max_new):
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 len(prompt) + j)
        tok = int(jax.random.categorical(key, logits[0] / temperature))
        ref.append(tok)
        logits, cache = llama.decode_step(
            params, cache, jnp.asarray([tok], jnp.int32),
            jnp.asarray(len(prompt) + j, jnp.int32), CFG)
    got = run_one(engine, prompt, max_new=max_new,
                  temperature=temperature, seed=seed)
    assert got == ref


def test_paged_kernels_match_dense(params):
    """prefill_chunk_paged + decode_slots_paged must produce the same
    logits as the dense prefill_chunk + decode_slots for the same
    tokens — pages only move the bytes, never the math."""
    rng = np.random.default_rng(23)
    prompt = rng.integers(1, CFG.vocab_size, size=13).astype(np.int32)
    nrows, pps = 2, CFG.max_seq // PS
    dense = llama.init_kv_cache(CFG, nrows)
    paged = llama.init_paged_kv_cache(CFG, nrows * pps + 1, PS)
    # Slot 1 of the dense cache <-> an arbitrary scattered page set.
    tables = np.zeros((nrows, pps), dtype=np.int32)
    tables[1] = np.arange(1, pps + 1)[::-1]
    tables = jnp.asarray(tables)
    slot = jnp.asarray(1, jnp.int32)
    # Whole-prompt prefill in one chunk (tail-padded).
    buf = np.zeros((16,), dtype=np.int32)
    buf[:len(prompt)] = prompt
    lg_d, dense = llama.prefill_chunk(
        params, dense, jnp.asarray(buf), slot, jnp.asarray(0, jnp.int32),
        CFG, last_idx=jnp.asarray(len(prompt) - 1, jnp.int32))
    lg_p, paged = llama.prefill_chunk_paged(
        params, paged, tables, jnp.asarray(buf), slot,
        jnp.asarray(0, jnp.int32),
        jnp.asarray(len(prompt), jnp.int32), CFG, PS)
    np.testing.assert_allclose(np.asarray(lg_d), np.asarray(lg_p),
                               rtol=1e-5, atol=1e-5)
    # A few decode steps on top, greedy-chained.
    tok_d, tok_p = (jnp.argmax(lg_d, -1).astype(jnp.int32),
                    jnp.argmax(lg_p, -1).astype(jnp.int32))
    for step in range(4):
        pos = np.full((nrows,), CFG.max_seq, dtype=np.int32)
        pos[1] = len(prompt) + step
        toks_d = jnp.zeros((nrows,), jnp.int32).at[1].set(tok_d)
        toks_p = jnp.zeros((nrows,), jnp.int32).at[1].set(tok_p)
        # Dense parks idle rows at max_seq - 1; paged routes >= max_seq
        # to the scratch page.
        pos_d = np.minimum(pos, CFG.max_seq - 1)
        lg_d, dense = llama.decode_slots(params, dense, toks_d,
                                         jnp.asarray(pos_d), CFG)
        lg_p, paged = llama.decode_slots_paged(params, paged, tables,
                                               toks_p, jnp.asarray(pos),
                                               CFG, PS)
        np.testing.assert_allclose(np.asarray(lg_d[1]),
                                   np.asarray(lg_p[1]),
                                   rtol=1e-5, atol=1e-5)
        tok_d, tok_p = (jnp.argmax(lg_d[1], -1).astype(jnp.int32),
                        jnp.argmax(lg_p[1], -1).astype(jnp.int32))
        assert int(tok_d) == int(tok_p)


# -- engine: prefix hit parity ------------------------------------------------

def test_prefix_hit_greedy_bit_for_bit(engine, params):
    rng = np.random.default_rng(31)
    prompt = [int(t) for t in rng.integers(1, CFG.vocab_size, size=21)]
    ref = reference_tokens(params, prompt, 10)
    cold = run_one(engine, prompt, max_new=10)
    assert cold == ref
    hits0, saved0 = engine.prefix_hits, engine.prefix_tokens_saved
    warm = run_one(engine, prompt, max_new=10)
    assert warm == ref, "prefix-hit output diverged from cold output"
    assert engine.prefix_hits == hits0 + 1
    # 21 tokens: the 2 fully-covered pages (16 tokens) are indexed and
    # shared; the 5-token tail was never indexed (only full pages are),
    # so it re-prefills.
    assert engine.prefix_tokens_saved - saved0 == 16


def test_multi_turn_session_extends_prefix(engine, params):
    """Turn 2's prompt = turn-1 prompt + turn-1 output + new tokens:
    the radix must hand back the whole shared history."""
    rng = np.random.default_rng(37)
    turn1 = [int(t) for t in rng.integers(1, CFG.vocab_size, size=16)]
    out1 = run_one(engine, turn1, max_new=8)
    assert out1 == reference_tokens(params, turn1, 8)
    turn2 = turn1 + out1 + [int(t) for t in
                            rng.integers(1, CFG.vocab_size, size=5)]
    saved0 = engine.prefix_tokens_saved
    out2 = run_one(engine, turn2, max_new=8)
    assert out2 == reference_tokens(params, turn2, 8)
    # turn-1's 16 prompt tokens are 2 indexed pages; the rest of turn 2
    # (turn-1's output) was freshly prefilled at turn 1's *generation*
    # time into decode pages, which are never indexed — so >= 16 saved.
    assert engine.prefix_tokens_saved - saved0 >= 16


def test_prefix_hit_sampled_bit_for_bit(params):
    """Seeded sampling: a prefix-hit request must reproduce the cold
    request's tokens exactly — per-request fold_in streams make the
    draw independent of how much prefill the hit skipped."""
    cold_eng = SlotEngine(params, CFG, num_slots=2, chunk=8,
                          page_size=PS, prefix_cache=False)
    warm_eng = SlotEngine(params, CFG, num_slots=2, chunk=8,
                          page_size=PS)
    rng = np.random.default_rng(41)
    prompt = [int(t) for t in rng.integers(1, CFG.vocab_size, size=19)]
    cold = run_one(cold_eng, prompt, max_new=12, temperature=0.8,
                   seed=1234)
    assert run_one(cold_eng, prompt, max_new=12, temperature=0.8,
                   seed=1234) == cold, "sampling is not deterministic"
    # Warm engine: first run populates the radix, second hits it.
    assert run_one(warm_eng, prompt, max_new=12, temperature=0.8,
                   seed=1234) == cold
    hits0 = warm_eng.prefix_hits
    assert run_one(warm_eng, prompt, max_new=12, temperature=0.8,
                   seed=1234) == cold
    assert warm_eng.prefix_hits == hits0 + 1


def test_cow_fork_divergence(engine, params):
    """Two sessions fork from a shared prefix mid-page and diverge; COW
    must keep every page intact: both match their cold references, and
    the original prompt still replays clean afterwards."""
    rng = np.random.default_rng(43)
    shared = [int(t) for t in rng.integers(1, CFG.vocab_size, size=12)]
    a = shared + [int(t) for t in rng.integers(1, CFG.vocab_size, size=6)]
    b = shared + [int(t) for t in rng.integers(1, CFG.vocab_size, size=7)]
    ref_a = reference_tokens(params, a, 8)
    ref_b = reference_tokens(params, b, 8)
    assert run_one(engine, a, max_new=8) == ref_a  # seeds the radix
    saved0 = engine.prefix_tokens_saved
    # Concurrent fork: both match `a`'s first page + 4 COW tokens.
    ha = engine.submit(a, max_new=8)
    hb = engine.submit(b, max_new=8)
    drain(engine, [ha, hb])
    assert ha.result(timeout=0).tokens == ref_a
    assert hb.result(timeout=0).tokens == ref_b
    assert engine.prefix_tokens_saved > saved0
    # The shared pages survived both writers: replay is still clean.
    assert run_one(engine, a, max_new=8) == ref_a


# -- accounting / eviction ----------------------------------------------------

def test_page_accounting_drains_clean(params):
    eng = SlotEngine(params, CFG, num_slots=2, chunk=8, page_size=PS)
    assert eng.pages_total == 2 * (CFG.max_seq // PS) + 1, \
        "pool must cost one scratch PAGE, not a scratch slot-row"
    rng = np.random.default_rng(47)
    handles = [eng.submit(
        [int(t) for t in rng.integers(1, CFG.vocab_size, size=n)],
        max_new=4) for n in (5, 11, 9, 17, 6)]
    drain(eng, handles)
    for h in handles:
        assert len(h.result(timeout=0).tokens) == 4
    # Invariant at rest: every page is either on the free list, held by
    # the radix index, or the scratch page.
    assert eng.pages_used + eng.pages_free == eng.pages_total
    assert np.all(eng._tables == 0), "drained slots must unmap pages"
    held = eng.pages_used - 1  # minus scratch
    assert held == eng.prefix_cache_len(), \
        "resident pages at rest must all be radix-held"
    freed = eng.clear_prefix_cache()
    assert freed == held
    assert eng.pages_used == 1, "only the scratch page may remain"
    # Scratch is reserved: never allocated, never refcounted.
    assert eng._pool.refcount(0) == 0


def test_whole_pool_request_with_partial_hit_admits(params):
    """A request whose worst-case footprint needs every allocatable
    page, arriving with a PARTIAL radix match, must still admit: the
    partial borrow pins its source page without reducing the fresh-page
    need, so admission has to drop the borrow (not livelock retrying
    forever with the pin in place)."""
    eng = SlotEngine(params, CFG, num_slots=1, chunk=8, page_size=PS,
                     num_pages=5)  # scratch + 4 allocatable
    rng = np.random.default_rng(61)
    base = [int(t) for t in rng.integers(1, CFG.vocab_size, size=16)]
    assert len(run_one(eng, base, max_new=4)) == 4  # seeds the radix
    # Shares 10 leading tokens -> 1 full page + a partial; needs
    # ceil((20+12)/8) = 4 pages == the whole allocatable pool.
    fork = base[:10] + [int(t) for t in
                        rng.integers(1, CFG.vocab_size, size=10)]
    tokens = run_one(eng, fork, max_new=12)
    assert tokens == reference_tokens(params, fork, 12)
    assert eng.pages_used + eng.pages_free == eng.pages_total


def test_lru_eviction_under_pool_pressure(params):
    """A pool with zero headroom forces radix eviction at admission:
    distinct prompts keep rotating through, correctness holds, and the
    pool never leaks."""
    eng = SlotEngine(params, CFG, num_slots=2, chunk=8, page_size=PS)
    rng = np.random.default_rng(53)
    for i in range(6):
        # 100-token prompts: 13 pages each; two in flight exhaust the
        # 32-page pool, so admission must evict earlier radix entries.
        prompt = [int(t) for t in
                  rng.integers(1, CFG.vocab_size, size=100)]
        assert run_one(eng, prompt, max_new=4) == \
            reference_tokens(params, prompt, 4), f"round {i} diverged"
        assert eng.pages_used + eng.pages_free == eng.pages_total
    assert eng.pages_free >= 0


# -- bounded admission --------------------------------------------------------

def test_bounded_pending_sheds_with_typed_error(params):
    eng = SlotEngine(params, CFG, num_slots=1, chunk=8, page_size=PS,
                     max_pending=2)
    eng.warmup()
    prompt = [3, 141, 59, 26, 5]
    keep = [eng.submit(prompt, max_new=4) for _ in range(2)]
    eng.step()  # admits the first into the slot; queue holds one
    keep.append(eng.submit(prompt, max_new=4))  # queue back at the cap
    with pytest.raises(OverloadedError):
        eng.submit(prompt, max_new=4)
    assert eng.requests_shed == 1
    drain(eng, keep)
    for h in keep:
        assert len(h.result(timeout=0).tokens) == 4


def test_queue_timeout_expires_pending_only(params):
    import time

    eng = SlotEngine(params, CFG, num_slots=1, chunk=8, page_size=PS,
                     queue_timeout_s=0.2)
    eng.warmup()
    prompt = [9, 2, 77, 31]
    resident = eng.submit(prompt, max_new=4)
    eng.step()  # admits `resident` into the slot before `late` arrives
    late = eng.submit(prompt, max_new=4)
    time.sleep(0.3)  # `late` (still queued — slot busy) expires
    drain(eng, [resident, late])
    assert len(resident.result(timeout=0).tokens) == 4, \
        "resident session must ride out the shed"
    with pytest.raises(OverloadedError):
        late.result(timeout=0)
