"""bench.py --smoke: the bench scenarios can't bitrot between rounds.

Runs the real bench entrypoint in a subprocess (it owns its runtime and
serve instance) with BENCH_SMOKE_FAST=1 — tiny windows, every scenario
code path: core microbench (paired actor-vs-task + put-vs-memcpy ratios,
copy counts) and the mixed HTTP + direct-handle + streaming stage with
p50/p99 latency output.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_runs_all_stages():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SMOKE_FAST"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=400, env=env, cwd=REPO)
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith("RESULT::")), None)
    assert line is not None, (
        f"no RESULT:: line rc={proc.returncode}\n"
        f"stdout: {proc.stdout[-800:]}\nstderr: {proc.stderr[-800:]}")
    result = json.loads(line[len("RESULT::"):])

    assert "core_microbench_error" not in result, result
    micro = result["core_microbench"]
    # The acceptance-criteria keys must exist and be sane.
    assert micro["1_1_actor_calls_sync"] > 0
    assert micro["single_client_tasks_sync"] > 0
    assert micro["actor_vs_task_sync"] > 0
    assert 0 < micro["put_large_(10MB)_vs_memcpy"] <= 2.0
    # Copy-count profile: a 10MB put is exactly ONE frame write and a
    # get is zero copies (zero-copy views out of the arena).
    assert micro["put_large_(10MB)_copies_per_op"] == 1.0
    assert micro["put_large_(10MB)_flatten_copies_per_op"] == 0.0
    assert micro["get_large_(10MB)_copies_per_op"] == 0

    assert "serve_mixed_error" not in result, result
    mixed = result["serve_mixed"]
    assert "errors" not in mixed, mixed
    # Every traffic class moved AND reported tail latency.
    for klass in ("http", "handle"):
        assert mixed[f"{klass}_reqs_per_s"] > 0, mixed
        assert mixed[f"{klass}_p50_ms"] > 0, mixed
        assert mixed[f"{klass}_p99_ms"] >= mixed[f"{klass}_p50_ms"], mixed
    assert mixed["stream_tokens_per_s"] > 0, mixed
    assert mixed["stream_first_chunk_p99_ms"] >= \
        mixed["stream_first_chunk_p50_ms"]

    # Serve chaos stage (ISSUE 18): a replica SIGKILLed under live
    # traffic — every request must end success / typed 503 / typed
    # deadline (zero hangs, zero raw 500s) and the controller must
    # replace the corpse, committing the replacement latency.
    assert "serve_chaos_error" not in result, result
    chaos = result["serve_chaos"]
    assert chaos["kills"] >= 1, chaos
    counts = chaos["counts"]
    assert counts["hung"] == 0, chaos
    assert counts["raw_500"] == 0, chaos
    assert counts["other"] == 0, chaos
    assert counts["ok"] > 0, chaos
    assert chaos["replaced_ms_p50"] > 0, chaos
    assert chaos["replaced_ms_p99"] >= chaos["replaced_ms_p50"], chaos
    assert chaos["during_kill_p99_ms"] >= 0, chaos

    # Telemetry plane wired through the bench: the mid-bench /metrics
    # scrape must see runtime counters AND worker/replica-shipped series
    # (latency histograms travel worker -> head over the pipe).
    assert "telemetry_scrape_error" not in result, result
    scrape = result["telemetry_scrape"]
    assert scrape["rt_tasks_submitted_total"] > 0, scrape
    assert scrape["rt_tasks_finished_total"] > 0, scrape
    assert scrape["rt_task_latency_seconds_count"] > 0, scrape
    assert scrape["rt_workers_alive"] > 0, scrape
    assert scrape["rt_serve_requests_total"] > 0, scrape
    assert scrape["rt_serve_request_latency_count"] > 0, scrape

    # Paged-KV multi-turn sessions (ISSUE 15): warm turns must hit the
    # radix prefix cache and beat cold TTFT. The full bench commits the
    # >= 2x criterion; the smoke gate is deliberately looser (1.5x) so
    # a loaded CI host can't flake it, while still catching a prefix
    # cache that stopped caching (speedup ~1x, hit rate 0).
    assert "llm_sessions_error" not in result, result
    sess = result["llm_sessions"]
    assert sess["prefix_hit_rate"] > 0, sess
    assert sess["ttft_cold_ms_p50"] > 0 and sess["ttft_warm_ms_p50"] > 0
    assert sess["warm_ttft_speedup"] >= 1.5, sess
    assert sess["prefix_tokens_saved"] > 0, sess

    # Stateful-session chaos stage (ISSUE 19): drain mid-traffic AND
    # SIGKILL mid-generation — sessions migrate (KV page export/import)
    # or recover (transcript re-prefill), continuations stay bit-for-bit
    # (greedy AND seeded), and no request is dropped: zero raw 500s,
    # zero hangs, zero drain-caused 503s.
    assert "llm_drain_error" not in result, result
    ld = result["llm_drain"]
    assert ld["drain"]["error"] is None, ld
    assert ld["drain"]["sessions_migrated"] >= 1, ld
    assert ld["drain"]["migrate_errors"] == 0, ld
    assert ld["drain"]["timed_out"] is False, ld
    assert ld["kills"] >= 1, ld
    dcounts = ld["counts"]
    assert dcounts["raw_500"] == 0, ld
    assert dcounts["hung"] == 0, ld
    assert dcounts["other"] == 0, ld
    assert dcounts["ok"] > 0, ld
    assert ld["drain_503"] == 0, ld
    assert ld["parity_greedy"] is True, ld
    assert ld["parity_seeded"] is True, ld
    assert ld["migrate_ms_p50"] > 0, ld
    assert ld["migrate_ms_p99"] >= ld["migrate_ms_p50"], ld
    assert ld["recovery_samples"] >= 1, ld
    assert ld["recovery_ms_p50"] > 0, ld

    # Long-gen decode + roofline stage (ISSUE 17): sustained decode
    # tok/s with the decode block committed next to the roofline
    # fraction, plus the tp2 parity sub-stage — under the test env's
    # virtual devices it must run and hold bit-for-bit (a single-device
    # host skips it cleanly instead).
    assert "llm_longgen_error" not in result, result
    lg = result["llm_longgen"]
    assert lg["tokens_per_s_longgen"] > 0, lg
    assert lg["decode_block"] >= 1, lg
    assert lg["decode_steps"] > 0, lg
    assert lg["roofline_frac"] >= 0, lg
    assert lg["bytes_per_step"] > 0, lg
    if isinstance(lg.get("tp2"), str):
        assert lg["tp2"].startswith("skipped"), lg
    else:
        assert lg["tp2_token_parity"] is True, lg
        assert "tp" in lg["tp2_kv_spec"], lg

    # Flight-recorder stage (ISSUE 16): per-stage task latency joined
    # head-side with worker exec deltas, stage sums ~= end-to-end, and
    # the LLM half commits per-request timing + the decode roofline
    # fraction — which must also be visible in the /metrics scrape.
    assert "bench_flight_error" not in result, result
    fl = result["bench_flight"]
    assert "task_join_timeout" not in fl, fl
    assert fl["task_rows_joined"] > 0, fl
    for stage in ("queue", "sched", "exec", "transfer", "total"):
        assert fl[f"task_{stage}_ms_p50"] >= 0, fl
        assert fl[f"task_{stage}_ms_p99"] >= fl[f"task_{stage}_ms_p50"], fl
    assert fl["task_exec_ms_p50"] > 0, fl
    # By construction queue+sched+exec+transfer == total; the 10%
    # acceptance tolerance leaves room for clamping on degenerate rows.
    assert abs(fl["task_stage_sum_frac_mean"] - 1.0) <= 0.1, fl
    assert fl["llm_requests"] > 0, fl
    for key in ("llm_prefill_ms_p50", "llm_decode_ms_p50",
                "llm_total_ms_p50"):
        assert fl[key] > 0, fl
    assert fl["llm_decode_steps"] > 0, fl
    assert fl["rt_llm_roofline_frac"] > 0, fl
    assert scrape["rt_task_stage_seconds_count"] > 0, scrape
    assert scrape["rt_llm_stage_seconds_count"] > 0, scrape
    assert scrape["rt_llm_roofline_frac"] > 0, scrape

    # Head-failover recovery stage: subprocess heads on a shared WAL —
    # the chaos loop must actually kill and recover, committing latency.
    # (The stage degrades gracefully on toolchain-less hosts, matching
    # the build_native() skips of the dedicated failover tests.)
    assert "head_failover_error" not in result, result
    hf = result["head_failover"]
    if hf.get("error") != "native toolchain unavailable":
        assert "error" not in hf, hf
        assert hf["kills"] >= 1, hf
        assert hf["recoveries"] >= 1, hf
        assert hf["actors_restarted_total"] >= 1, hf
        assert hf["recover_ms_p50"] > 0, hf
        assert hf["recover_ms_p99"] >= hf["recover_ms_p50"], hf

    # Tracing-overhead A/B stage (ISSUE 20): paired traced/untraced
    # child runs must both execute, the traced child must actually
    # record spans, and the committed overhead figure must stay sane.
    # The 5% budget is enforced against the FULL bench run (see
    # BASELINE.md); smoke windows are short enough that scheduler noise
    # dominates, so the smoke gate is deliberately loose.
    assert "tracing_overhead_error" not in result, result
    to = result["tracing_overhead"]
    assert "error" not in to, to
    assert to["tasks_per_s_traced"] > 0, to
    assert to["tasks_per_s_untraced"] > 0, to
    assert to["spans_traced"] > 0, to
    assert to["spans_untraced"] == 0, to
    assert len(to["pair_ratios"]) >= 2, to
    assert to["overhead_frac"] <= 0.35, to
