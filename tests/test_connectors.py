"""Connector pipeline tests (reference: rllib/connectors/tests/)."""

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (
    ActionConnectorPipeline,
    AgentConnectorPipeline,
    ClipActionConnector,
    ClipRewardConnector,
    ConnectorContext,
    FrameStackConnector,
    MeanStdObsConnector,
    NormalizeActionConnector,
    create_connectors_for_policy,
    restore_connectors_for_policy,
)


def _ctx(**kw):
    defaults = dict(obs_shape=(4,), num_actions=2, num_envs=3)
    defaults.update(kw)
    return ConnectorContext(**defaults)


def test_frame_stack_stacks_and_resets():
    ctx = _ctx()
    fs = FrameStackConnector(ctx, k=3)
    o1 = np.ones((2, 4), np.float32)
    out = fs(o1)
    assert out.shape == (2, 12)
    np.testing.assert_array_equal(out, np.ones((2, 12)))
    o2 = 2 * np.ones((2, 4), np.float32)
    out = fs(o2)
    # history shifts: [o1, o1, o2]
    np.testing.assert_array_equal(out[:, :8], np.ones((2, 8)))
    np.testing.assert_array_equal(out[:, 8:], 2 * np.ones((2, 4)))
    # env slot 0 finishes; its next frame is a reset obs and must fill
    # the whole history (no leakage from the dead episode).
    fs.on_episode_done(np.array([True, False]))
    o3 = np.stack([7 * np.ones(4), 3 * np.ones(4)]).astype(np.float32)
    out = fs(o3)
    np.testing.assert_array_equal(out[0], 7 * np.ones(12))
    np.testing.assert_array_equal(out[1, 8:], 3 * np.ones(4))
    np.testing.assert_array_equal(out[1, 4:8], 2 * np.ones(4))


def test_mean_std_normalizes_and_freezes_in_eval():
    ctx = _ctx()
    ms = MeanStdObsConnector(ctx)
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 3.0, size=(200, 4)).astype(np.float32)
    for i in range(0, 200, 20):
        out = ms(data[i:i + 20])
    # After plenty of data, outputs are ~standardized.
    assert abs(float(out.mean())) < 0.5
    assert 0.5 < float(out.std()) < 2.0
    count = ms.count
    ms.in_eval()
    ms(np.zeros((10, 4), np.float32))
    assert ms.count == count  # frozen


def test_mean_std_serialization_round_trip():
    ctx = _ctx()
    ms = MeanStdObsConnector(ctx)
    rng = np.random.default_rng(1)
    ms(rng.normal(2.0, 0.5, size=(64, 4)).astype(np.float32))
    name, params = ms.to_state()
    ms2 = MeanStdObsConnector.from_state(ctx, params)
    x = rng.normal(2.0, 0.5, size=(8, 4)).astype(np.float32)
    ms.in_eval(), ms2.in_eval()
    np.testing.assert_allclose(ms(x), ms2(x), rtol=1e-6)


def test_action_normalize_then_clip():
    ctx = _ctx(action_low=np.array([-2.0]), action_high=np.array([2.0]))
    pipe = ActionConnectorPipeline(
        ctx, [NormalizeActionConnector(ctx), ClipActionConnector(ctx)])
    a = np.array([[-1.0], [0.0], [1.0], [5.0]], np.float32)
    out = pipe(a)
    np.testing.assert_allclose(out[:, 0], [-2.0, 0.0, 2.0, 2.0])


def test_clip_reward_sign_and_limit():
    ctx = _ctx()
    sign = ClipRewardConnector(ctx, sign=True)
    np.testing.assert_array_equal(
        sign.transform_reward(np.array([-3.0, 0.0, 9.1])), [-1, 0, 1])
    lim = ClipRewardConnector(ctx, limit=1.5)
    np.testing.assert_allclose(
        lim.transform_reward(np.array([-3.0, 0.5, 9.1])), [-1.5, 0.5, 1.5])


def test_pipeline_spec_and_restore_round_trip():
    ctx = _ctx()
    agent, action = create_connectors_for_policy(ctx, {
        "agent": [("FrameStack", {"k": 2}), "MeanStdObs",
                  ("ClipReward", {"limit": 1.0})],
        "action": ["ImmutableAction"],
    })
    obs = np.random.default_rng(2).normal(size=(3, 4)).astype(np.float32)
    out = agent(obs)
    assert out.shape == (3, 8)
    state = {"agent": agent.to_state(), "action": action.to_state()}
    agent2, action2 = restore_connectors_for_policy(ctx, state)
    agent.in_eval(), agent2.in_eval()
    # FrameStack history is runtime state (not serialized); feed the same
    # obs twice so both pipelines are warmed identically.
    np.testing.assert_allclose(agent(obs), agent2(obs), rtol=1e-6)
    acts = action2(np.array([1, 0, 1]))
    with pytest.raises(ValueError):
        acts[0] = 5  # immutable


def test_pipeline_insert_remove():
    ctx = _ctx()
    agent, _ = create_connectors_for_policy(
        ctx, {"agent": ["MeanStdObs"]})
    agent.prepend(FrameStackConnector(ctx, k=2))
    assert [type(c).__name__ for c in agent.connectors] == \
        ["FrameStackConnector", "MeanStdObsConnector"]
    agent.remove("MeanStdObs")
    assert len(agent.connectors) == 1


def test_rollout_worker_with_connectors_learns_shapes():
    """RolloutWorker builds its policy against the TRANSFORMED obs shape
    and records transformed obs in the batch."""
    from ray_tpu.rllib.rollout_worker import RolloutWorker
    from ray_tpu.rllib.sample_batch import OBS

    w = RolloutWorker(
        "FastCartPole", num_envs=4,
        policy_config={"connectors": {
            "agent": [("FrameStack", {"k": 2}), "MeanStdObs"],
            "action": ["ImmutableAction"],
        }},
    )
    assert w._connected_obs_shape == (8,)
    batch = w.sample(rollout_length=16)
    assert batch[OBS].shape == (16, 4, 8)
    state = w.connector_state()
    assert [n for n, _ in state["agent"]] == \
        ["FrameStack", "MeanStdObs", ]
    assert [n for n, _ in state["action"]] == ["ImmutableAction"]


def test_connector_state_survives_algorithm_checkpoint(tmp_path):
    """MeanStd statistics ride the Algorithm save/restore round trip
    (a restored policy must see the SAME normalization it trained on)."""
    from ray_tpu.rllib.rollout_worker import RolloutWorker

    w = RolloutWorker("FastCartPole", num_envs=4, policy_config={
        "connectors": {"agent": ["MeanStdObs"]}})
    w.sample(rollout_length=32)
    ms = w.agent_connectors.connectors[0]
    assert ms.count > 0
    state = w.connector_state()

    w2 = RolloutWorker("FastCartPole", num_envs=4, policy_config={
        "connectors": {"agent": ["MeanStdObs"]}})
    w2.restore_connector_state(state)
    ms2 = w2.agent_connectors.connectors[0]
    assert ms2.count == ms.count
    np.testing.assert_allclose(ms2.mean, ms.mean)


def test_external_env_rejects_slot_stateful_and_probes_shape():
    from ray_tpu.rllib.external import ExternalEnv, ExternalEnvWorker

    class Dummy(ExternalEnv):
        def __init__(self):
            super().__init__(obs_shape=(4,), num_actions=2)

        def run(self):
            import time
            time.sleep(60)

    with pytest.raises(ValueError, match="slot-stateful"):
        ExternalEnvWorker(Dummy(), policy_config={
            "connectors": {"agent": [("FrameStack", {"k": 4})]}})

    # MeanStdObs is fine, the probe must not pollute its statistics,
    # and the policy input dim follows the transformed shape.
    w = ExternalEnvWorker(Dummy(), policy_config={
        "connectors": {"agent": ["MeanStdObs"]}})
    assert w._connected_obs_shape == (4,)
    assert w.agent_connectors.connectors[0].count == 0
