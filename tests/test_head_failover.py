"""Head failover: the control plane survives a SIGKILLed head.

Reference coverage analog: GCS fault-tolerance tests — the gcs_server
restarts, reloads its tables from storage (``gcs_table_storage.h``), and
``GcsActorManager::ReconstructActor`` re-runs creation for actors whose
workers died while the head was down.

Here each "head" is a driver subprocess running the native control store
on a shared WAL (``control_store_persist_path``). Killing it with
SIGKILL is a real head-host crash: no teardown, workers orphaned, WAL
possibly torn mid-append. The replacement head must re-resolve named
actors, restart them under ``max_restarts``, and complete queued calls.
"""

import os
import signal
import time

import pytest

from ray_tpu.core.gcs_socket import build_native

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.skipif(
        not build_native(), reason="native toolchain unavailable"),
]


# Driver script for the basic failover cycle: creates a named actor and
# a placement group on first run; on every later run resolves the actor
# by name, submits a call (queued while the actor restarts), and reports
# the recovery outcome.
_SRC_BASIC = r"""
import time
import ray_tpu as rt
from ray_tpu.core import runtime as _rtm

rt.init(num_cpus=2)


@rt.remote
class Counter:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


try:
    h = rt.get_actor("survivor")
    created = 0
except ValueError:
    h = Counter.options(name="survivor", max_restarts=5).remote()
    rt.placement_group([{"CPU": 1.0}], strategy="PACK", name="pg0")
    created = 1
ref = h.bump.remote()  # queued: the recovered actor is still restarting
v = rt.get(ref, timeout=120)
rep = _rtm.get_head_runtime().recovery_report or {}
print("HEADKILLER_READY value=%d created=%d restarted=%d dead=%d pgs=%d "
      "actor=%s" % (v, created, rep.get("actors_restarted", 0),
                    rep.get("actors_dead", 0), rep.get("pgs_restored", 0),
                    h._actor_id.hex()), flush=True)
while True:
    rt.get(h.bump.remote())
    time.sleep(0.005)
"""


# Driver script for restart exhaustion across failovers: max_restarts=1
# buys exactly ONE head failover; the second replacement head must mark
# the actor DEAD with a typed, explanatory death cause and drop its name.
_SRC_EXHAUST = r"""
import time
import ray_tpu as rt
from ray_tpu.core import runtime as _rtm
from ray_tpu.core.gcs import ActorState

rt.init(num_cpus=2)


@rt.remote
class C:
    def ping(self):
        return "pong"


head = _rtm.get_head_runtime()
try:
    h = rt.get_actor("exhaust_me")
    rt.get(h.ping.remote(), timeout=60)
    print("HEADKILLER_READY value=1 created=0 outcome=alive", flush=True)
    while True:
        rt.get(h.ping.remote())
        time.sleep(0.005)
except ValueError:
    infos = [i for i in head.gcs.actors.values() if i.name == "exhaust_me"]
    if infos:
        info = infos[0]
        dead = int(info.state == ActorState.DEAD)
        cause_ok = int(bool(info.death_cause
                            and "max_restarts" in info.death_cause))
        # A surviving handle (the WAL-durable KV blob) must fail TYPED —
        # refs resolve to ActorDiedError with the cause, not a raise of
        # 'unknown actor' at submit time.
        from ray_tpu.core import serialization as _ser
        typed = 0
        blob = head.gcs.kv_get(b"actor_handle:" + info.actor_id.binary(),
                               "actors")
        if blob is not None:
            h2 = _ser.loads(blob)
            try:
                rt.get(h2.ping.remote(), timeout=30)
            except rt.ActorDiedError as e:
                typed = int(bool(getattr(e, "death_cause", None)
                                 and "max_restarts" in e.death_cause))
            except Exception:
                typed = 0
        print("HEADKILLER_READY value=0 created=0 outcome=dead dead=%d "
              "cause_ok=%d typed=%d" % (dead, cause_ok, typed), flush=True)
        time.sleep(3600)
    else:
        h = C.options(name="exhaust_me", max_restarts=1).remote()
        rt.get(h.ping.remote(), timeout=60)
        print("HEADKILLER_READY value=1 created=1 outcome=created",
              flush=True)
        while True:
            rt.get(h.ping.remote())
            time.sleep(0.005)
"""


def test_head_failover_named_actor_and_queued_call(tmp_path):
    """SIGKILL the head mid-workload; the replacement head (same WAL)
    re-resolves the named actor, restarts it, completes the queued call,
    and reschedules the persisted placement group."""
    from ray_tpu.cluster_utils import HeadKiller

    killer = HeadKiller(str(tmp_path / "gcs.wal"), kill_after_s=0.3,
                        head_src=_SRC_BASIC)
    first = killer.run_cycle()  # creates, then is SIGKILLed mid-workload
    assert first["created"] == 1
    assert first["value"] == 1

    second = killer.run_cycle()  # replacement head on the same WAL
    assert second["created"] == 0, "named actor must re-resolve"
    assert second["actor"] == first["actor"], \
        "recovery must preserve the actor identity"
    assert second["restarted"] == 1, second
    # State is rebuilt by re-running the creation (standard max_restarts
    # semantics): the counter starts fresh and the queued call completes.
    assert second["value"] == 1
    assert second["pgs"] == 1, "persisted placement group must reschedule"
    assert len(killer.killed) == 2


def test_head_failover_chaos_loop(tmp_path):
    """Chaos loop: kill the head every cycle; every replacement recovers
    the SAME actor with sane recovery latency samples."""
    from ray_tpu.cluster_utils import HeadKiller

    killer = HeadKiller(str(tmp_path / "gcs.wal"), kill_after_s=0.2)
    samples = killer.run(cycles=3)
    recoveries = [s for s in samples if not s["created"]]
    assert len(recoveries) == 2
    actor_ids = {s["actor"] for s in samples}
    assert len(actor_ids) == 1, "one identity across every failover"
    for s in recoveries:
        assert s["restarted"] == 1, s
        assert s["recover_ms"] > 0
        assert s["total_ms"] >= s["recover_ms"]


def test_head_failover_restart_exhaustion_typed_death(tmp_path):
    """max_restarts=1 buys exactly one failover; the second replacement
    head marks the actor DEAD with an explanatory death cause and the
    name stops resolving."""
    from ray_tpu.cluster_utils import HeadKiller

    killer = HeadKiller(str(tmp_path / "gcs.wal"), kill_after_s=0.2,
                        head_src=_SRC_EXHAUST)
    first = killer.run_cycle()
    assert first["outcome"] == "created"
    second = killer.run_cycle()  # consumes the single allowed restart
    assert second["outcome"] == "alive"
    third = killer.run_cycle()
    assert third["outcome"] == "dead", third
    assert third["dead"] == 1
    assert third["cause_ok"] == 1, \
        "death_cause must name the exhausted max_restarts"
    assert third["typed"] == 1, \
        "a surviving handle must fail with a typed ActorDiedError"
    # The tombstone must keep working across FURTHER failovers: the
    # restored DEAD record still routes handle submits to the typed
    # dead-actor path with the persisted cause.
    fourth = killer.run_cycle()
    assert fourth["outcome"] == "dead", fourth
    assert fourth["typed"] == 1, \
        "typed death_cause must survive repeated failovers"


def test_actor_died_error_carries_death_cause(rt_init):
    """Satellite: pending callers of a dead actor get a TYPED
    ActorDiedError whose death_cause explains the death (not a generic
    failure)."""
    import ray_tpu as rt

    @rt.remote(max_restarts=0)
    class B:
        def pid(self):
            return os.getpid()

        def slow(self):
            time.sleep(30)
            return 1

    b = B.remote()
    pid = rt.get(b.pid.remote())
    ref = b.slow.remote()  # in-flight when the worker dies
    time.sleep(0.3)
    os.kill(pid, signal.SIGKILL)
    with pytest.raises(rt.ActorDiedError) as ei:
        rt.get(ref, timeout=30)
    assert ei.value.death_cause == "worker died"
    # Subsequent submissions surface the recorded cause too.
    with pytest.raises(rt.ActorDiedError) as ei2:
        rt.get(b.pid.remote(), timeout=30)
    assert ei2.value.death_cause and "worker died" in ei2.value.death_cause


def test_max_restarts_exhaustion_death_cause(rt_init):
    """Satellite: exhausting max_restarts names the budget in the death
    cause surfaced to callers."""
    import ray_tpu as rt

    @rt.remote(max_restarts=1, max_task_retries=1)
    class B:
        def pid(self):
            return os.getpid()

        def slow(self):
            time.sleep(30)
            return 1

    b = B.remote()
    pid1 = rt.get(b.pid.remote())
    os.kill(pid1, signal.SIGKILL)
    # Wait for the restart to complete (calls retry/buffer meanwhile).
    deadline = time.monotonic() + 60
    pid2 = pid1
    while pid2 == pid1 and time.monotonic() < deadline:
        pid2 = rt.get(b.pid.remote(), timeout=60)
    assert pid2 != pid1
    ref = b.slow.remote()
    time.sleep(0.3)
    os.kill(pid2, signal.SIGKILL)  # second death: budget exhausted
    with pytest.raises(rt.ActorDiedError) as ei:
        rt.get(ref, timeout=30)
    assert ei.value.death_cause == "worker died (max_restarts=1 exhausted)"


def test_pubsub_callback_errors_logged_and_counted(caplog):
    """Satellite: a raising subscriber callback is no longer swallowed —
    it logs at warning and bumps rt_pubsub_callback_errors."""
    import logging

    from ray_tpu.core.gcs import Pubsub
    from ray_tpu.observability.metrics import registry

    ps = Pubsub()
    ps.subscribe("CHAOS", lambda msg: 1 / 0)
    before = 0.0
    ctr = registry.get("rt_pubsub_callback_errors")
    if ctr is not None:
        before = sum(ctr.collect()[1].values())
    with caplog.at_level(logging.WARNING, logger="ray_tpu.core.gcs"):
        ps.publish("CHAOS", "boom")
    assert any("pubsub subscriber callback failed" in r.message
               for r in caplog.records)
    ctr = registry.get("rt_pubsub_callback_errors")
    assert ctr is not None
    assert sum(ctr.collect()[1].values()) == before + 1


@pytest.mark.slow
def test_daemon_rejoins_replacement_head(tmp_path):
    """A node daemon that outlives its head re-dials the fixed cluster
    port and is adopted by the replacement head as fresh capacity."""
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    src_a = (
        "import time\n"
        "import ray_tpu as rt\n"
        "rt.init(num_cpus=2)\n"
        "print('HEAD_A_READY', flush=True)\n"
        "while True:\n"
        "    time.sleep(0.5)\n"
    )
    src_b = (
        "import time\n"
        "import ray_tpu as rt\n"
        "from ray_tpu.core import runtime as _rtm\n"
        "rt.init(num_cpus=2)\n"
        "head = _rtm.get_head_runtime()\n"
        "deadline = time.time() + 30\n"
        "n = 1\n"
        "while time.time() < deadline:\n"
        "    n = len(head.scheduler.nodes())\n"
        "    if n >= 2:\n"
        "        break\n"
        "    time.sleep(0.2)\n"
        "print('HEAD_B_NODES %d' % n, flush=True)\n"
        "rt.shutdown()\n"  # daemons get a clean stop (no rejoin loop)
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "RT_NATIVE_CONTROL_STORE": "1",
        "RT_CONTROL_STORE_PERSIST_PATH": str(tmp_path / "gcs.wal"),
        "RT_NODE_DAEMONS": "1",
        "RT_DAEMON_REJOIN_ATTEMPTS": "60",
        "RT_CLUSTER_LISTENER_PORT": str(port),
        "RT_OBJECT_STORE_MEMORY": str(64 * 1024 * 1024),
        "JAX_PLATFORMS": "cpu",
        "RT_JAX_PLATFORM": "cpu",
        "PYTHONUNBUFFERED": "1",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    a = subprocess.Popen([sys.executable, "-c", src_a], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    try:
        for line in a.stdout:
            if line.startswith("HEAD_A_READY"):
                break
        time.sleep(0.5)  # let the daemon settle
    finally:
        a.send_signal(signal.SIGKILL)
        a.wait()
        a.stdout.close()
    out = subprocess.run([sys.executable, "-c", src_b], env=env,
                         capture_output=True, text=True, timeout=120)
    line = next((ln for ln in out.stdout.splitlines()
                 if ln.startswith("HEAD_B_NODES")), None)
    assert line is not None, out.stdout[-500:]
    assert int(line.split()[1]) >= 2, \
        f"surviving daemon did not rejoin: {line}"
