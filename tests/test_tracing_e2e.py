"""End-to-end request tracing (ISSUE 20).

Covers the tentpole acceptance criterion — one HTTP request through the
Serve proxy yields a span tree from >= 3 distinct processes under ONE
trace id, queryable by the ``x-request-id`` the proxy returned, via
``rt trace --json`` — plus the satellites: trace-store LRU/sampling/
tail-retention units with counted evictions, the cross-process
actor-submit trace regression, the replacement-head clean start, the
metrics history ring behind ``/api/history`` / ``rt top``, and the
``rt metrics --json`` / name-prefix filter.
"""

import contextlib
import json
import os
import time
import urllib.request

import pytest


# ---------------------------------------------------------------- helpers

def _ev(trace_id, name="s", dur_us=1000.0, span_id=None, parent=None,
        error=None, ts=0.0, pid=4242):
    """One chrome-form span event, the wire shape tracestore ingests."""
    args = {"trace_id": trace_id, "span_id": span_id or os.urandom(8).hex(),
            "parent_id": parent}
    if error:
        args["error"] = error
    return {"name": name, "ph": "X", "cat": "span", "ts": ts,
            "dur": dur_us, "pid": pid, "args": args}


@contextlib.contextmanager
def _cfg_env(**overrides):
    """Apply RT_* config overrides for the block, then restore."""
    from ray_tpu.core.config import Config

    saved = {}
    for k, v in overrides.items():
        key = "RT_" + k.upper()
        saved[key] = os.environ.get(key)
        os.environ[key] = str(v)
    Config.reset()
    try:
        yield
    finally:
        for key, v in saved.items():
            if v is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = v
        Config.reset()


def _dropped(buffer: str) -> float:
    from ray_tpu.observability.metrics import registry

    entry = registry.collect_all().get("rt_telemetry_dropped_total")
    if entry is None:
        return 0.0
    return float(entry[1].get((("buffer", buffer),), 0.0))


def _kept(reason: str) -> float:
    from ray_tpu.observability.metrics import registry

    entry = registry.collect_all().get("rt_trace_store_kept_total")
    if entry is None:
        return 0.0
    return float(entry[1].get((("reason", reason),), 0.0))


# ------------------------------------------------------- trace store units

def test_tracestore_lru_eviction_counted():
    from ray_tpu.observability import tracestore

    with _cfg_env(trace_store_max_traces=4, trace_sample_rate=1.0):
        tracestore.clear()
        base_drop, base_kept = _dropped("tracestore"), _kept("sampled")
        ids = [f"trace{i:02d}" + "0" * 24 for i in range(6)]
        for tid in ids:
            tracestore.ingest_event(_ev(tid))
        assert tracestore.stats()["traces"] == 4
        # Oldest two evicted, newest four resident.
        assert tracestore.get_trace(ids[0]) is None
        assert tracestore.get_trace(ids[1]) is None
        assert tracestore.get_trace(ids[-1]) is not None
        assert _dropped("tracestore") - base_drop == 2
        assert _kept("sampled") - base_kept == 6
        tracestore.clear()


def test_tracestore_sampling_deterministic_and_probation():
    from ray_tpu.observability import tracestore

    with _cfg_env(trace_sample_rate=0.5):
        verdicts = {tid: tracestore.sampled(tid)
                    for tid in (os.urandom(16).hex() for _ in range(64))}
        # Deterministic: same id, same verdict, every time.
        for tid, v in verdicts.items():
            assert tracestore.sampled(tid) == v
        # A 0.5 rate over 64 ids lands strictly between the extremes.
        kept = sum(verdicts.values())
        assert 0 < kept < 64
    with _cfg_env(trace_sample_rate=0.0):
        tracestore.clear()
        for i in range(5):
            tracestore.ingest_event(_ev(f"probation{i}" + "0" * 22))
        st = tracestore.stats()
        assert st["traces"] == 0  # sampled out: nothing admitted...
        assert st["probation"] == 5  # ...but parked for tail retention
        tracestore.clear()


def test_tracestore_tail_retention_promotes_slow_and_errored():
    from ray_tpu.observability import tracestore

    with _cfg_env(trace_sample_rate=0.0, trace_slow_ms=100.0):
        tracestore.clear()
        base_tail = _kept("tail")
        slow_tid, err_tid = "slowtrace" + "0" * 23, "errtrace" + "0" * 24
        # Fast span first: parks on probation.
        tracestore.ingest_event(_ev(slow_tid, name="fast", dur_us=50.0))
        assert tracestore.stats()["traces"] == 0
        # A slow span (>= trace_slow_ms) promotes the WHOLE trace,
        # probation spans included.
        tracestore.ingest_event(_ev(slow_tid, name="slow", dur_us=150e3))
        data = tracestore.get_trace(slow_tid)
        assert data is not None and data["retention"] == "tail"
        assert {s["name"] for s in data["spans"]} == {"fast", "slow"}
        # An errored span promotes too, regardless of duration.
        tracestore.ingest_event(_ev(err_tid, dur_us=10.0, error="boom"))
        err = tracestore.get_trace(err_tid)
        assert err is not None and err["retention"] == "tail"
        assert _kept("tail") - base_tail == 2
        tracestore.clear()


def test_tracestore_per_trace_span_cap_counted():
    from ray_tpu.observability import tracestore

    with _cfg_env(trace_sample_rate=1.0):
        tracestore.clear()
        base = _dropped("tracestore_spans")
        tid = "capcheck" + "0" * 24
        for _ in range(tracestore._SPANS_PER_TRACE_MAX + 20):
            tracestore.ingest_event(_ev(tid))
        data = tracestore.get_trace(tid)
        assert len(data["spans"]) == tracestore._SPANS_PER_TRACE_MAX
        assert _dropped("tracestore_spans") - base == 20
        tracestore.clear()


def test_tracer_ring_trim_counted():
    """Satellite 2: the tracer's bounded ring counts trims in
    rt_telemetry_dropped_total{buffer="tracer"} instead of silently
    dropping the oldest spans."""
    from ray_tpu.observability import tracing

    tracer = tracing.Tracer(max_spans=8)
    tracer.enable()
    base = _dropped("tracer")
    for i in range(11):
        tracer.record(tracing.Span(
            name=f"s{i}", span_id=f"{i:016x}", parent_id=None,
            trace_id="t" * 32, start_s=0.0, end_s=1.0))
    assert len(tracer.spans()) == 8
    assert _dropped("tracer") - base == 3


# ------------------------------------------------------- history ring unit

def test_history_ring_rates_and_percentile_carry_forward():
    from ray_tpu.observability import telemetry
    from ray_tpu.observability.metrics import (Counter, Histogram,
                                               get_or_create)

    telemetry.clear_history()
    tasks = get_or_create(Counter, "rt_tasks_finished", "Tasks finished",
                          ("state",))
    ttft = get_or_create(Histogram, "rt_llm_ttft_seconds",
                         "Submit-to-first-token latency",
                         boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                     1.0, 5.0, 30.0])
    t0 = 1_000_000.0
    s0 = telemetry.record_history_sample(now=t0)
    assert s0 is not None and s0["tasks_per_s"] == 0.0  # no prev sample
    tasks.inc(30.0, tags={"state": "DONE"})
    ttft.observe(0.03)
    ttft.observe(0.03)
    s1 = telemetry.record_history_sample(now=t0 + 10.0)
    assert s1["tasks_per_s"] == pytest.approx(3.0)
    # Window percentile interpolates inside the winning bucket
    # (0.01..0.05 here).
    assert 10.0 <= s1["ttft_p50_ms"] <= 50.0
    p50 = s1["ttft_p50_ms"]
    # Quiet window: no new observations -> the estimate carries forward
    # instead of collapsing to zero between scrapes.
    s2 = telemetry.record_history_sample(now=t0 + 20.0)
    assert s2["ttft_p50_ms"] == p50
    assert s2["tasks_per_s"] == 0.0
    h = telemetry.history(limit=2)
    assert [s["ts"] for s in h["samples"]] == [s1["ts"], s2["ts"]]
    assert h["interval_ms"] > 0
    telemetry.clear_history()
    assert telemetry.history()["samples"] == []


def test_history_ring_bounded():
    from ray_tpu.observability import telemetry

    telemetry.clear_history()
    for i in range(telemetry._HISTORY_MAX + 25):
        telemetry.record_history_sample(now=1_000_000.0 + i)
    assert len(telemetry.history()["samples"]) == telemetry._HISTORY_MAX
    telemetry.clear_history()


# --------------------------------------------------- live-runtime fixtures

@contextlib.contextmanager
def _traced_runtime(**extra_env):
    """Fresh runtime with tracing on (mirrors test_telemetry's helper);
    restores config/env/tracer state afterwards."""
    import ray_tpu as rt
    from ray_tpu.core.config import Config
    from ray_tpu.observability import telemetry, tracestore, tracing

    if rt.is_initialized():
        rt.shutdown()
    overrides = {"RT_TRACING_ENABLED": "1",
                 "RT_METRICS_REPORT_INTERVAL_MS": "200"}
    overrides.update({"RT_" + k.upper(): str(v)
                      for k, v in extra_env.items()})
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    Config.reset()
    telemetry.clear()
    rt.init(num_cpus=4)
    try:
        yield rt
    finally:
        rt.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        Config.reset()
        tracing.disable()
        tracing.get_tracer().clear()
        tracing.get_tracer().on_record = None
        tracestore.clear()
        telemetry.clear()


def _wait_trace(trace_id, pred, timeout=25.0):
    from ray_tpu.observability import tracestore

    deadline = time.monotonic() + timeout
    data = None
    while time.monotonic() < deadline:
        data = tracestore.get_trace(trace_id)
        if data is not None and pred(data):
            return data
        time.sleep(0.25)
    return data


# ----------------------------------------------------- tentpole e2e (HTTP)

def test_serve_request_trace_spans_three_processes(capsys):
    """THE acceptance criterion: one HTTP request -> `rt trace <rid>`
    shows proxy -> router -> replica -> nested task spans from >= 3
    distinct processes under the single trace id the proxy returned in
    the x-request-id response header."""
    with _traced_runtime():
        import ray_tpu as rt
        from ray_tpu import serve
        from ray_tpu.scripts import cli

        serve.start(http_port=18621)
        try:
            @rt.remote
            def nested(x):
                return x * 2

            @serve.deployment
            class Echo:
                async def __call__(self, payload):
                    # The nested task MUST join the request's trace:
                    # its submit happens inside the replica's async
                    # handler, two processes away from the proxy.
                    ref = nested.remote(int(payload.get("x", 0)))
                    from ray_tpu.core import get

                    return {"doubled": get(ref, timeout=30)}

            serve.run(Echo.bind(), name="Echo")
            rid = "e2etrace" + os.urandom(8).hex()
            body = json.dumps({"x": 21}).encode()
            req = urllib.request.Request(
                "http://127.0.0.1:18621/Echo", data=body,
                headers={"Content-Type": "application/json",
                         "x-request-id": rid})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert json.loads(r.read()) == {"doubled": 42}
                # The proxy echoes the request id on the response.
                assert r.headers.get("x-request-id") == rid

            data = _wait_trace(rid, lambda d: len(d["procs"]) >= 3)
            assert data is not None, "trace never landed in the store"
            assert len(data["procs"]) >= 3, data["procs"]
            names = {s["name"] for s in data["spans"]}
            assert "proxy.request" in names
            assert "router.assign" in names
            assert "replica.handle" in names
            assert any(n.startswith("task.execute") for n in names)
            # Every span shares the request's trace id.
            assert all(s["trace_id"] == rid for s in data["spans"])

            # Same tree through the CLI (`rt trace <id> --json`).
            assert cli.main(["trace", rid, "--json"]) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["trace_id"] == rid
            assert len(out["procs"]) >= 3
            # Human rendering includes the proc labels.
            assert cli.main(["trace", rid]) == 0
            text = capsys.readouterr().out
            assert "proxy.request" in text and "[driver]" in text

            # A request WITHOUT a client id gets a minted one back.
            req = urllib.request.Request(
                "http://127.0.0.1:18621/Echo", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                minted = r.headers.get("x-request-id")
            assert minted and len(minted) == 32
        finally:
            serve.shutdown()


def test_actor_call_trace_crosses_processes():
    """Satellite 1 regression: an actor method call stamps trace_ctx on
    the submit path, so the worker-side execute span joins the
    driver-side actor.submit span's trace."""
    with _traced_runtime():
        import ray_tpu as rt
        from ray_tpu.observability import tracing

        @rt.remote
        class Ping:
            def ping(self):
                return "pong"

        a = Ping.remote()
        assert rt.get(a.ping.remote(), timeout=30) == "pong"
        submit = next(s for s in tracing.get_tracer().spans("actor.submit")
                      if "ping" in s.name)
        data = _wait_trace(
            submit.trace_id,
            lambda d: any(s["name"].startswith("task.execute")
                          for s in d["spans"]))
        assert data is not None
        execs = [s for s in data["spans"]
                 if s["name"].startswith("task.execute")]
        assert execs, data["spans"]
        # The execute span ran in a different process than the driver.
        assert execs[0]["pid"] != os.getpid()
        assert len(data["procs"]) >= 2


def test_replacement_head_starts_with_clean_trace_store():
    """A replacement head after failover must not serve the dead
    head's traces (mirrors flight.clear() in Runtime.__init__)."""
    import ray_tpu as rt
    from ray_tpu.observability import tracestore

    if rt.is_initialized():
        rt.shutdown()
    stale = "stalehead" + "0" * 23
    tracestore.ingest_event(_ev(stale))
    assert tracestore.get_trace(stale) is not None
    rt.init(num_cpus=1)
    try:
        assert tracestore.get_trace(stale) is None
        assert tracestore.stats()["traces"] == 0
    finally:
        rt.shutdown()


def test_llm_request_trace_engine_stage_spans_match_timing():
    """The SlotEngine synthesizes llm.admission/queue/prefill/decode
    child spans from the PR-16 timing metadata — the acceptance
    criterion pins them to the response's own ``timing`` dict within
    10%."""
    with _traced_runtime():
        from ray_tpu import serve
        from ray_tpu.llm import build_llm_app

        serve.start(http_port=18622)
        try:
            app = build_llm_app(model="llama-tiny", num_slots=2, chunk=8,
                                seed=0, name="llmtrace")
            serve.run(app)
            rid = "llmtrace" + os.urandom(8).hex()
            body = json.dumps({"prompt": [3, 141, 59, 26, 5],
                               "max_tokens": 8}).encode()
            req = urllib.request.Request(
                "http://127.0.0.1:18622/llmtrace", data=body,
                headers={"Content-Type": "application/json",
                         "x-request-id": rid})
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
                assert r.headers.get("x-request-id") == rid
            timing = out["timing"]
            assert timing["total_s"] > 0

            data = _wait_trace(
                rid, lambda d: any(s["name"] == "llm.request"
                                   for s in d["spans"]))
            assert data is not None
            spans = {s["name"]: s for s in data["spans"]}
            assert "llm.request" in spans, sorted(spans)
            # Engine spans came from the replica process, the proxy root
            # from the head: >= 2 processes under the request's id.
            assert len(data["procs"]) >= 2, data["procs"]
            for stage in ("admission", "queue", "prefill", "decode"):
                name = f"llm.{stage}"
                assert name in spans, sorted(spans)
                want_ms = timing[f"{stage}_s"] * 1e3
                got_ms = spans[name]["dur_ms"]
                assert got_ms == pytest.approx(want_ms, rel=0.1,
                                               abs=0.05), (
                    f"{name}: span {got_ms}ms vs timing {want_ms}ms")
            assert spans["llm.request"]["dur_ms"] == pytest.approx(
                timing["total_s"] * 1e3, rel=0.1, abs=0.1)
            # Stage spans nest under the request span, which nests
            # under the proxy root.
            root_id = spans["llm.request"]["span_id"]
            assert spans["llm.decode"]["parent_id"] == root_id
            assert (spans["llm.request"]["parent_id"]
                    == spans["proxy.request"]["span_id"])
        finally:
            serve.shutdown()


# ----------------------------------------- dashboard routes and `rt top`

def test_dashboard_history_traces_routes_and_rt_top(rt_shared, capsys):
    from ray_tpu.observability import (start_dashboard, stop_dashboard,
                                       telemetry, tracestore)
    from ray_tpu.scripts import cli

    tracestore.clear()
    tracestore.ingest_event(_ev("dashtrace" + "0" * 23, name="root"))
    telemetry.record_history_sample()
    start_dashboard(port=18623)
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:18623/api/history", timeout=10) as r:
            hist = json.loads(r.read())
        assert hist["interval_ms"] > 0
        assert hist["samples"], "history ring empty"
        sample = hist["samples"][-1]
        for key in ("ts", "tasks_per_s", "tokens_per_s", "workers",
                    "load_1m", "mem_used_frac"):
            assert key in sample, sample
        with urllib.request.urlopen(
                "http://127.0.0.1:18623/api/traces", timeout=10) as r:
            idx = json.loads(r.read())
        assert idx["stats"]["traces"] >= 1
        tid = idx["traces"][-1]["trace_id"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:18623/api/traces/{tid}",
                timeout=10) as r:
            one = json.loads(r.read())
        assert one["trace_id"] == tid and one["spans"]
        # `rt top --once`: one rendered frame over HTTP.
        assert cli.main(["top", "--url", "http://127.0.0.1:18623",
                         "--once"]) == 0
        frame = capsys.readouterr().out
        assert "tasks/s" in frame and "workers" in frame
    finally:
        stop_dashboard()
        tracestore.clear()


# ------------------------------------------------------------ rt metrics

def test_rt_metrics_json_and_prefix_filter(rt_shared, capsys):
    from ray_tpu.scripts import cli

    assert cli.main(["metrics", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "rt_tasks_submitted" in out
    entry = out["rt_tasks_submitted"]
    assert entry["kind"] == "counter"
    assert isinstance(entry["series"], list)
    for s in entry["series"]:
        assert set(s) == {"tags", "value"}

    # Name-prefix filter narrows both forms.
    assert cli.main(["metrics", "rt_workers_", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out and all(k.startswith("rt_workers_") for k in out)

    assert cli.main(["metrics", "rt_workers_"]) == 0
    text = capsys.readouterr().out
    sample_lines = [ln for ln in text.splitlines()
                    if ln and not ln.startswith("#")]
    assert sample_lines
    assert all(ln.startswith("rt_workers_") for ln in sample_lines)
