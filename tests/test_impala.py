"""IMPALA tests: V-trace math, async learner loop, learning smoke.

Reference coverage analog: rllib/algorithms/impala/tests/test_impala.py
and test_vtrace.py (V-trace vs ground truth on hand-checkable cases).
"""

import numpy as np
import pytest


def test_vtrace_on_policy_reduces_to_td_lambda_targets():
    """With rho == 1 (on-policy) and c == 1, vs equals the discounted
    Monte-Carlo/bootstrap targets of the trajectory."""
    import jax.numpy as jnp

    from ray_tpu.rllib import vtrace

    t_len, n = 4, 1
    rewards = jnp.ones((t_len, n))
    dones = jnp.zeros((t_len, n))
    values = jnp.zeros((t_len, n))
    logp = jnp.zeros((t_len, n))  # behavior == target
    bootstrap = jnp.zeros((n,))
    vs, pg_adv = vtrace(logp, logp, rewards, dones, values, bootstrap,
                        gamma=1.0)
    # vs[t] = sum of future rewards (4, 3, 2, 1); advantage equals it too
    np.testing.assert_allclose(np.asarray(vs)[:, 0], [4, 3, 2, 1], atol=1e-5)
    np.testing.assert_allclose(np.asarray(pg_adv)[:, 0], [4, 3, 2, 1],
                               atol=1e-5)


def test_vtrace_clips_large_ratios():
    import jax.numpy as jnp

    from ray_tpu.rllib import vtrace

    t_len, n = 3, 1
    rewards = jnp.ones((t_len, n))
    dones = jnp.zeros((t_len, n))
    values = jnp.zeros((t_len, n))
    behavior = jnp.zeros((t_len, n))
    target = jnp.full((t_len, n), 5.0)  # rho = e^5, clipped to 1
    bootstrap = jnp.zeros((n,))
    vs_clipped, _ = vtrace(behavior, target, rewards, dones, values,
                           bootstrap, gamma=1.0)
    vs_onpolicy, _ = vtrace(behavior, behavior, rewards, dones, values,
                            bootstrap, gamma=1.0)
    np.testing.assert_allclose(np.asarray(vs_clipped),
                               np.asarray(vs_onpolicy), atol=1e-4)


def test_vtrace_respects_dones():
    import jax.numpy as jnp

    from ray_tpu.rllib import vtrace

    rewards = jnp.ones((3, 1))
    dones = jnp.asarray([[0.0], [1.0], [0.0]])
    values = jnp.zeros((3, 1))
    logp = jnp.zeros((3, 1))
    vs, _ = vtrace(logp, logp, rewards, dones, values,
                   jnp.full((1,), 100.0), gamma=1.0)
    # Episode ends at t=1: vs[0] = 1 + 1 = 2, no leakage of the huge
    # bootstrap across the boundary; vs[2] = 1 + 100 (bootstrap applies).
    np.testing.assert_allclose(np.asarray(vs)[:, 0], [2, 1, 101], atol=1e-4)


def test_impala_sync_iteration(rt_shared):
    from ray_tpu.rllib import ImpalaConfig

    algo = (ImpalaConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=16)
            .training(num_batches_per_iter=2)
            .build())
    result = algo.train()
    assert result["timesteps_this_iter"] == 2 * 4 * 16
    assert result["num_learner_updates"] == 2
    assert np.isfinite(result["loss"])
    algo.stop()


def test_impala_async_workers(rt_shared):
    from ray_tpu.rllib import ImpalaConfig

    algo = (ImpalaConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=16)
            .training(num_batches_per_iter=4)
            .build())
    r1 = algo.train()
    assert r1["num_learner_updates"] == 4
    assert r1["timesteps_this_iter"] == 4 * 2 * 16
    r2 = algo.train()  # in-flight pipeline keeps flowing across iters
    assert r2["num_learner_updates"] == 8
    algo.stop()


@pytest.mark.slow
def test_impala_learns_cartpole(rt_shared):
    from ray_tpu.rllib import ImpalaConfig

    algo = (ImpalaConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=64)
            .training(lr=3e-3, num_batches_per_iter=8, entropy_coeff=0.003)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(25):
        result = algo.train()
        r = result.get("episode_reward_mean")
        if r is not None:
            best = max(best, r)
        if best >= 100:
            break
    algo.stop()
    assert best >= 100, f"IMPALA failed to learn CartPole (best={best})"


def test_impala_conv_policy_smoke():
    """IMPALA learner uses the policy's own network apply (conv for
    image-shaped envs), not a hardcoded MLP."""
    from ray_tpu.rllib import ImpalaConfig

    config = (ImpalaConfig()
              .environment("AtariSim")
              .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                        rollout_fragment_length=4)
              .training(num_batches_per_iter=1))
    algo = config.build()
    try:
        result = algo.train()
        assert result["timesteps_this_iter"] >= 8
    finally:
        algo.stop()
