"""Dask-protocol scheduler tests (reference: ray/util/dask tests).

dask itself is not installed here; the graphs below are hand-built to
the dask graph spec (dict of key -> (callable, *args) with key
references), which is exactly what dask.compute hands a scheduler."""

import operator

import pytest

from ray_tpu.util.dask_backend import enable_dask, ray_tpu_dask_get


def test_simple_chain(rt_shared):
    dsk = {
        "x": 1,
        "y": (operator.add, "x", 10),
        "z": (operator.mul, "y", "y"),
    }
    assert ray_tpu_dask_get(dsk, "z") == 121
    assert ray_tpu_dask_get(dsk, ["z", "y"]) == [121, 11]
    assert ray_tpu_dask_get(dsk, [["z"], ["x", "y"]]) == [[121], [1, 11]]


def test_parallel_branches_and_tuple_keys(rt_shared):
    # dask.array-style tuple keys + tree reduction.
    dsk = {("chunk", i): (operator.mul, i, i) for i in range(8)}
    dsk["sum"] = (sum, [("chunk", i) for i in range(8)])
    assert ray_tpu_dask_get(dsk, "sum") == sum(i * i for i in range(8))


def test_nested_task_expressions(rt_shared):
    dsk = {
        "a": 3,
        "b": (operator.add, (operator.mul, "a", 2), 1),  # nested task
        "c": (list, (range, "a")),
    }
    assert ray_tpu_dask_get(dsk, "b") == 7
    assert ray_tpu_dask_get(dsk, "c") == [0, 1, 2]


def test_literals_pass_through(rt_shared):
    dsk = {"k": (operator.add, "not-a-key", "!")}
    # "not-a-key" is not in the graph: treated as a literal string.
    assert ray_tpu_dask_get(dsk, "k") == "not-a-key!"


def test_errors(rt_shared):
    with pytest.raises(KeyError, match="missing"):
        ray_tpu_dask_get({"a": 1}, "missing")
    dsk = {"a": (operator.add, "b", 1), "b": (operator.add, "a", 1)}
    with pytest.raises(ValueError, match="cycle"):
        ray_tpu_dask_get(dsk, "a")


def test_enable_dask_gated():
    try:
        import dask  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="dask"):
            enable_dask()
    else:
        enable_dask()
