"""Tune tests: search spaces, Tuner loop, ASHA early stopping, PBT.

Mirrors reference coverage in ``python/ray/tune/tests/``.
"""

import time

import pytest


def test_grid_and_random_expansion():
    from ray_tpu.tune import BasicVariantGenerator, grid_search, uniform

    gen = BasicVariantGenerator(
        {"a": grid_search([1, 2, 3]), "b": uniform(0, 1), "c": "fixed"},
        num_samples=2, seed=0,
    )
    seen = []
    while True:
        cfg = gen.suggest("t")
        if cfg is None:
            break
        seen.append(cfg)
    assert len(seen) == 6
    assert sorted({c["a"] for c in seen}) == [1, 2, 3]
    assert all(0 <= c["b"] <= 1 and c["c"] == "fixed" for c in seen)


def test_tuner_basic(rt_shared):
    from ray_tpu.tune import Tuner, grid_search, report

    def objective(config):
        report({"score": config["x"] ** 2})

    results = Tuner(
        objective, param_space={"x": grid_search([1, 2, 3])}
    ).fit()
    assert len(results.trials) == 3
    best = results.get_best_result("score", mode="min")
    assert best.config["x"] == 1
    assert best.last_result["score"] == 1


def test_tune_run_multiple_reports(rt_shared):
    from ray_tpu.tune import report, run

    def objective(config):
        for i in range(4):
            report({"loss": 10.0 / (i + 1), "step": i})

    results = run(objective, config={"lr": 0.1}, num_samples=2)
    assert len(results.trials) == 2
    for t in results.trials:
        assert t.status == "TERMINATED"
        assert len(t.results) == 4
        assert t.last_result["training_iteration"] == 4


def test_asha_stops_bad_trials(rt_shared):
    from ray_tpu.tune import AsyncHyperBandScheduler, Tuner, TuneConfig, grid_search, report

    def objective(config):
        # Trial quality is determined by "quality"; bad trials plateau high.
        for i in range(20):
            loss = config["quality"] + 10.0 / (i + 1)
            report({"loss": loss})
            time.sleep(0.01)

    scheduler = AsyncHyperBandScheduler(
        metric="loss", mode="min", grace_period=2, reduction_factor=2,
        max_t=20,
    )
    results = Tuner(
        objective,
        param_space={"quality": grid_search([0.0, 0.0, 50.0, 50.0])},
        tune_config=TuneConfig(scheduler=scheduler,
                               max_concurrent_trials=4),
    ).fit()
    # Bad trials must be cut early; good trials must reach max_t (they end
    # as STOPPED too — ASHA stops at max_t — so compare iterations).
    bad = [t for t in results.trials if t.config["quality"] == 50.0]
    good = [t for t in results.trials if t.config["quality"] == 0.0]
    assert any(t.iteration < 20 for t in bad), [t.iteration for t in bad]
    assert any(t.iteration == 20 for t in good), [t.iteration for t in good]


def test_error_trial_reported(rt_shared):
    from ray_tpu.tune import Tuner, grid_search

    def objective(config):
        if config["x"] == 2:
            raise RuntimeError("bad trial")
        from ray_tpu.tune import report

        report({"score": config["x"]})

    results = Tuner(
        objective, param_space={"x": grid_search([1, 2])}
    ).fit()
    statuses = {t.config["x"]: t.status for t in results.trials}
    assert statuses[1] == "TERMINATED"
    assert statuses[2] == "ERROR"
    assert len(results.errors) == 1
    assert "bad trial" in results.errors[0]


def test_pbt_exploits(rt_shared):
    from ray_tpu.train import Checkpoint
    from ray_tpu.tune import (
        PopulationBasedTraining,
        Tuner,
        TuneConfig,
        grid_search,
        report,
    )
    from ray_tpu.train.session import get_checkpoint

    def objective(config):
        ck = get_checkpoint()
        start = ck.to_dict()["level"] if ck else 0.0
        lr = config["lr"]
        level = start
        for i in range(15):
            # Higher lr climbs faster; PBT should propagate high-lr configs.
            level += lr
            report({"score": level},
                   checkpoint=Checkpoint.from_dict({"level": level}))
            time.sleep(0.01)

    scheduler = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0, 5.0]}, seed=1,
    )
    results = Tuner(
        objective,
        param_space={"lr": grid_search([0.1, 0.1, 5.0])},
        tune_config=TuneConfig(scheduler=scheduler,
                               max_concurrent_trials=3),
    ).fit()
    best = results.get_best_result("score", mode="max")
    assert best.last_result["score"] > 10  # exploited trials climbed


def test_concurrency_limiter(rt_init):
    """Wrapped searchers never exceed max_concurrent in-flight trials
    (reference: tune/search/concurrency_limiter.py)."""
    import ray_tpu as rt
    from ray_tpu import tune
    from ray_tpu.tune import ConcurrencyLimiter, Tuner, TuneConfig
    from ray_tpu.tune.search import RandomSearch

    @rt.remote
    class Gauge:
        def __init__(self):
            self.cur = 0
            self.peak = 0

        def enter(self):
            self.cur += 1
            self.peak = max(self.peak, self.cur)

        def leave(self):
            self.cur -= 1

        def peak_value(self):
            return self.peak

    gauge = Gauge.remote()

    def trainable(config):
        import time

        import ray_tpu as rt2

        rt2.get(gauge.enter.remote())
        time.sleep(0.3)
        tune.report({"score": config["x"]})
        rt2.get(gauge.leave.remote())

    limiter = ConcurrencyLimiter(
        RandomSearch({"x": tune.uniform(0, 1)}, num_samples=6),
        max_concurrent=2)
    result = Tuner(
        trainable,
        tune_config=TuneConfig(max_concurrent_trials=4,
                               search_alg=limiter),
    ).fit()
    assert len(result.trials) == 6
    import ray_tpu as rt3

    peak = rt3.get(gauge.peak_value.remote())
    assert peak <= 2, f"limiter exceeded cap: {peak}"
