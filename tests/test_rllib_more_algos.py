"""A2C, ES/ARS, CQL, and contextual bandit tests
(reference: rllib/algorithms/{a2c,es,ars,cql,bandit}/tests)."""

import numpy as np
import pytest


def test_a2c_learns_cartpole(rt_shared):
    from ray_tpu.rllib import A2CConfig

    algo = (A2CConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=20)
            .training(lr=2e-3)
            .debugging(seed=1)
            .build())
    best = 0.0
    for _ in range(60):
        result = algo.train()
        best = max(best, result.get("episode_reward_mean") or 0.0)
        if best >= 100:
            break
    algo.stop()
    assert best >= 100, f"A2C failed to learn: best={best}"


def test_es_improves_cartpole(rt_shared):
    from ray_tpu.rllib import ESConfig

    algo = (ESConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=2)
            .training(episodes_per_batch=12, sigma=0.1, step_size=0.1,
                      noise_size=200_000)
            .debugging(seed=0)
            .build())
    algo.config.policy_config_extra["max_episode_steps"] = 200
    first = algo.evaluate(episodes=3)
    for _ in range(12):
        result = algo.train()
    final = algo.evaluate(episodes=3)
    algo.stop()
    # Gradient-free improvement: mean return strictly grows.
    assert final > first + 20, f"ES did not improve: {first} -> {final}"


def test_ars_improves_cartpole(rt_shared):
    from ray_tpu.rllib import ARSConfig

    algo = (ARSConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=2)
            .training(episodes_per_batch=12, sigma=0.1, step_size=0.15,
                      top_k=6, noise_size=200_000)
            .debugging(seed=3)
            .build())
    algo.config.policy_config_extra["max_episode_steps"] = 200
    first = algo.evaluate(episodes=3)
    for _ in range(12):
        algo.train()
    final = algo.evaluate(episodes=3)
    algo.stop()
    assert final > first + 20, f"ARS did not improve: {first} -> {final}"


def test_es_noise_table_deterministic():
    from ray_tpu.rllib import SharedNoiseTable

    t1 = SharedNoiseTable(10_000, seed=7)
    t2 = SharedNoiseTable(10_000, seed=7)
    np.testing.assert_array_equal(t1.get(123, 64), t2.get(123, 64))


def test_linucb_sublinear_regret():
    from ray_tpu.rllib import BanditEnv, LinUCB, run_bandit

    env = BanditEnv(num_arms=4, context_dim=8, noise=0.1, seed=0)
    out = run_bandit(LinUCB(4, 8, alpha=1.0), env, steps=2000)
    # The policy converges: late-window per-step regret far below the
    # early average, and cumulative regret well under the random-policy
    # linear growth (~0.5/step here).
    assert out["final_window_regret"] < 0.1, out["final_window_regret"]
    assert out["cumulative_regret"] < 400

    rand_env = BanditEnv(num_arms=4, context_dim=8, noise=0.1, seed=0)
    rng = np.random.default_rng(0)

    class RandomPolicy:
        def select_arm(self, x):
            return int(rng.integers(0, 4))

        def update(self, *a):
            pass

    rand = run_bandit(RandomPolicy(), rand_env, steps=2000)
    assert out["cumulative_regret"] < rand["cumulative_regret"] / 3


def test_lints_sublinear_regret():
    from ray_tpu.rllib import BanditEnv, LinTS, run_bandit

    env = BanditEnv(num_arms=4, context_dim=8, noise=0.1, seed=1)
    out = run_bandit(LinTS(4, 8, nu=0.3, seed=1), env, steps=2000)
    assert out["final_window_regret"] < 0.1
    assert out["cumulative_regret"] < 400


@pytest.fixture(scope="module")
def pendulum_dataset(tmp_path_factory):
    """Logged random-policy pendulum transitions for offline tests."""
    from ray_tpu.rllib.env import FastPendulum
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.sample_batch import (ACTIONS, DONES, NEXT_OBS,
                                            OBS, REWARDS, SampleBatch)

    path = str(tmp_path_factory.mktemp("cql_data"))
    env = FastPendulum(num_envs=8, seed=0)
    rng = np.random.default_rng(0)
    writer = JsonWriter(path)
    obs = env.vector_reset()
    for _ in range(120):
        acts = rng.uniform(-2, 2, size=(8, 1)).astype(np.float32)
        nobs, rews, dones, _ = env.vector_step(acts)
        writer.write(SampleBatch({
            OBS: obs.copy(), ACTIONS: acts, REWARDS: rews,
            NEXT_OBS: nobs.copy(), DONES: dones,
        }))
        obs = nobs
    writer.close()
    return path


def test_cql_trains_and_is_conservative(rt_shared, pendulum_dataset):
    from ray_tpu.rllib import CQLConfig

    algo = (CQLConfig()
            .offline_data(pendulum_dataset)
            .training(train_batch_size=128, num_updates_per_iter=50,
                      min_q_weight=5.0, bc_iters=50)
            .debugging(seed=0)
            .build())
    algo.config.action_dim = 1
    for _ in range(4):
        result = algo.train()
    assert np.isfinite(result["critic_loss"])
    # The defining CQL property: Q on dataset actions >= Q on random
    # (out-of-distribution) actions for the same states.
    obs = algo._data["obs"][:256]
    data_acts = algo._data["actions"][:256]
    rng = np.random.default_rng(1)
    rand_acts = rng.uniform(-2, 2, size=data_acts.shape).astype(
        np.float32)
    q_data = algo.q_values(obs, data_acts).mean()
    q_rand = algo.q_values(obs, rand_acts).mean()
    assert q_data > q_rand, (q_data, q_rand)
    act = algo.compute_single_action(obs[0])
    assert act.shape == (1,) and -2.0 <= float(act[0]) <= 2.0
    algo.stop()


def test_cql_penalty_widens_gap(rt_shared, pendulum_dataset):
    """min_q_weight > 0 produces a larger data-vs-random Q gap than
    weight 0 (the penalty is doing the work, not the TD loss)."""
    from ray_tpu.rllib import CQLConfig

    gaps = {}
    for w in (0.0, 5.0):
        algo = (CQLConfig()
                .offline_data(pendulum_dataset)
                .training(train_batch_size=128,
                          num_updates_per_iter=40, min_q_weight=w,
                          bc_iters=10_000)  # actor stays BC: isolate Q
                .debugging(seed=0)
                .build())
        for _ in range(3):
            algo.train()
        obs = algo._data["obs"][:256]
        data_acts = algo._data["actions"][:256]
        rand_acts = np.random.default_rng(1).uniform(
            -2, 2, size=data_acts.shape).astype(np.float32)
        gaps[w] = float(algo.q_values(obs, data_acts).mean()
                        - algo.q_values(obs, rand_acts).mean())
        algo.stop()
    assert gaps[5.0] > gaps[0.0], gaps
