"""Native control-store daemon tests: KV, node table + health, pubsub.

Reference coverage analog: gcs_server unit tests
(``src/ray/gcs/gcs_server/test/``) exercised over the real socket
protocol, like ``gcs_server_rpc_test.cc``.
"""

import time

import pytest

from ray_tpu.core.gcs_socket import (
    ControlStoreClient,
    ControlStoreError,
    ControlStoreProcess,
    build_native,
)

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable")


@pytest.fixture()
def store():
    proc = ControlStoreProcess()
    client = proc.client()
    yield client
    client.close()
    proc.stop()


def test_ping_and_stats(store):
    assert store.ping()
    s = store.stats()
    assert s == {"nodes": 0, "kv_entries": 0, "subscriber_channels": 0}


def test_kv_roundtrip(store):
    assert store.kv_get(b"missing") is None
    assert store.kv_put(b"k", b"v1")
    assert store.kv_get(b"k") == b"v1"
    # no-overwrite put is rejected
    assert not store.kv_put(b"k", b"v2", overwrite=False)
    assert store.kv_get(b"k") == b"v1"
    # namespaces are disjoint
    assert store.kv_get(b"k", namespace="other") is None
    store.kv_put(b"k2", b"x")
    store.kv_put(b"j1", b"y")
    assert sorted(store.kv_keys(b"k")) == [b"k", b"k2"]
    assert store.kv_del(b"k")
    assert store.kv_get(b"k") is None
    assert not store.kv_del(b"k")


def test_kv_large_value(store):
    blob = bytes(range(256)) * 4096  # 1 MiB
    store.kv_put(b"big", blob)
    assert store.kv_get(b"big") == blob


def test_node_lifecycle_and_health(store):
    store.register_node(b"node-1", b"info-1")
    store.register_node(b"node-2", b"info-2")
    nodes = {n["node_id"]: n for n in store.list_nodes()}
    assert nodes[b"node-1"]["alive"] and nodes[b"node-2"]["alive"]
    assert nodes[b"node-1"]["info"] == b"info-1"

    events = []
    store.subscribe("NODE", events.append)
    time.sleep(0.05)

    # node-2 stops heartbeating; health checker marks it dead.
    store.start_health_check(0.05, 2)
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        store.heartbeat(b"node-1")
        nodes = {n["node_id"]: n for n in store.list_nodes()}
        if not nodes[b"node-2"]["alive"]:
            break
        time.sleep(0.02)
    nodes = {n["node_id"]: n for n in store.list_nodes()}
    assert nodes[b"node-1"]["alive"], "heartbeating node must stay alive"
    assert not nodes[b"node-2"]["alive"], "silent node must be marked dead"
    time.sleep(0.05)
    assert b"DEAD:node-2" in events


def test_pubsub_fanout(store):
    got_a, got_b = [], []
    unsub_a = store.subscribe("chan", got_a.append)
    store.subscribe("chan", got_b.append)
    time.sleep(0.05)
    n = store.publish("chan", b"hello")
    assert n == 1  # one subscriber *connection* (fan-out client-side)
    deadline = time.monotonic() + 2.0
    while (not got_a or not got_b) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got_a == [b"hello"] and got_b == [b"hello"]

    unsub_a()
    store.publish("chan", b"again")
    time.sleep(0.2)
    assert got_a == [b"hello"]  # unsubscribed callback silent
    assert got_b == [b"hello", b"again"]


def test_publish_without_subscribers(store):
    assert store.publish("empty-channel", b"x") == 0


def test_multiple_clients_share_state(store):
    second = ControlStoreClient(store.address)
    try:
        store.kv_put(b"shared", b"value")
        assert second.kv_get(b"shared") == b"value"
        # Cross-client pubsub: publish from one, receive on the other.
        got = []
        second.subscribe("x", got.append)
        time.sleep(0.05)
        store.publish("x", b"cross")
        deadline = time.monotonic() + 2.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got == [b"cross"]
    finally:
        second.close()


def test_server_shutdown_via_protocol():
    proc = ControlStoreProcess()
    client = proc.client()
    client.kv_put(b"k", b"v")
    client.shutdown_server()
    deadline = time.monotonic() + 5.0
    while proc._proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    assert proc._proc.poll() is not None, "daemon must exit on SHUTDOWN"
    client.close()
