"""Direct-method and doubly-robust off-policy estimators (VERDICT r4
item 10): accuracy on a known synthetic MDP and the DR-variance <= IS
property that justifies the model-based machinery."""

import numpy as np
import pytest

from ray_tpu.rllib.offline import (DirectMethod, DoublyRobust,
                                   ImportanceSampling, SampleBatch)
from ray_tpu.rllib.sample_batch import (ACTIONS, DONES, LOGPS, NEXT_OBS,
                                        OBS, REWARDS)

D = 4  # obs feature dim
A = 2


def _reward(obs, act):
    """Known reward: action 1 is better when obs[0] > 0."""
    return np.where(act == 1, obs[:, 0], -obs[:, 0]).astype(np.float64)


def _target_probs(obs):
    obs = np.asarray(obs, np.float64)
    logits = np.stack([-2.0 * obs[:, 0], 2.0 * obs[:, 0]], axis=1)
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def _target_logp(obs, actions):
    p = _target_probs(obs)
    return np.log(p[np.arange(len(actions)),
                    np.asarray(actions).astype(np.int64)])


def _logged_bandit(n, rng):
    """One-step episodes, uniform-random behavior policy."""
    obs = rng.normal(size=(n, D)).astype(np.float32)
    act = rng.integers(0, A, size=n)
    rew = _reward(obs, act)
    return SampleBatch({
        OBS: obs,
        ACTIONS: act.astype(np.int64),
        REWARDS: rew.astype(np.float32),
        NEXT_OBS: np.zeros_like(obs),
        DONES: np.ones(n, bool),
        LOGPS: np.full(n, np.log(0.5), np.float32),
    })


def _true_v(obs):
    """Exact target value on these contexts (1-step, known reward)."""
    p = _target_probs(obs)
    per = p[:, 0] * _reward(obs, np.zeros(len(obs), np.int64)) + \
        p[:, 1] * _reward(obs, np.ones(len(obs), np.int64))
    return float(per.mean())


def _make(cls, **kw):
    return cls(_target_logp, target_probs_fn=_target_probs,
               num_actions=A, gamma=1.0, q_backups=5, **kw)


def test_dm_and_dr_recover_true_value():
    rng = np.random.default_rng(0)
    batch = _logged_bandit(2000, rng)
    truth = _true_v(np.asarray(batch[OBS]))
    dm = _make(DirectMethod).estimate(batch)
    dr = _make(DoublyRobust).estimate(batch)
    isv = ImportanceSampling(_target_logp, gamma=1.0).estimate(batch)
    for name, est in (("dm", dm), ("dr", dr), ("is", isv)):
        assert abs(est["v_target"] - truth) < 0.15, (
            f"{name}: {est['v_target']:.3f} vs truth {truth:.3f}")


def test_dr_variance_not_worse_than_is():
    """Across many small logged datasets, DR's estimator variance must
    not exceed ordinary IS's (the control variate earning its keep)."""
    rng = np.random.default_rng(1)
    is_est, dr_est = [], []
    for trial in range(12):
        batch = _logged_bandit(150, rng)
        is_est.append(ImportanceSampling(
            _target_logp, gamma=1.0).estimate(batch)["v_target"])
        dr_est.append(_make(DoublyRobust).estimate(batch)["v_target"])
    v_is = float(np.var(is_est))
    v_dr = float(np.var(dr_est))
    assert v_dr <= v_is * 1.05, (
        f"DR variance {v_dr:.4f} vs IS {v_is:.4f}")


def test_dr_multi_step_chain():
    """Two-step episodes: the backward recursion must discount and
    bootstrap correctly (not just the bandit special case)."""
    rng = np.random.default_rng(2)
    n_ep = 400
    obs0 = rng.normal(size=(n_ep, D)).astype(np.float32)
    act0 = rng.integers(0, A, size=n_ep)
    obs1 = rng.normal(size=(n_ep, D)).astype(np.float32)
    act1 = rng.integers(0, A, size=n_ep)
    rows = {
        OBS: np.empty((2 * n_ep, D), np.float32),
        NEXT_OBS: np.empty((2 * n_ep, D), np.float32),
        ACTIONS: np.empty(2 * n_ep, np.int64),
        REWARDS: np.empty(2 * n_ep, np.float32),
        DONES: np.tile([False, True], n_ep),
        LOGPS: np.full(2 * n_ep, np.log(0.5), np.float32),
    }
    rows[OBS][0::2], rows[OBS][1::2] = obs0, obs1
    rows[NEXT_OBS][0::2] = obs1
    rows[NEXT_OBS][1::2] = np.zeros_like(obs1)
    rows[ACTIONS][0::2], rows[ACTIONS][1::2] = act0, act1
    rows[REWARDS][0::2] = _reward(obs0, act0)
    rows[REWARDS][1::2] = _reward(obs1, act1)
    batch = SampleBatch(rows)
    gamma = 0.9
    truth = _true_v(obs0) + gamma * _true_v(obs1)
    dr = DoublyRobust(_target_logp, target_probs_fn=_target_probs,
                      num_actions=A, gamma=gamma,
                      q_backups=10).estimate(batch)
    assert abs(dr["v_target"] - truth) < 0.2, (
        f"DR {dr['v_target']:.3f} vs truth {truth:.3f}")
