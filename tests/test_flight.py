"""Flight recorder: stage-attributed task latency (ISSUE 16).

Reference coverage analog: the task-events backend tests
(``gcs_task_manager`` + ``ray summary tasks``) — lifecycle transition
timestamps recorded per task, worker exec durations joined head-side,
per-function per-stage aggregates served through the CLI and dashboard.

Covers: stamp monotonicity + stage-sum ≈ end-to-end (the acceptance
criterion), the worker exec-delta join over the telemetry pipe, the
``rt summary`` / ``rt list --state`` / ``rt logs`` CLI paths, the
``rt_telemetry_dropped_total`` satellite, clean-store re-init, and the
recorder surviving head failover (replacement head, fresh store).
"""

import json
import logging
import time

import pytest

from ray_tpu.core.gcs_socket import build_native


def _wait_joined(name: str, n: int, timeout: float = 25.0):
    """Poll until ``n`` tasks of ``name`` have their exec stage joined
    (worker deltas ride the ~1s telemetry flush)."""
    from ray_tpu.observability import recent_flight_tasks

    deadline = time.monotonic() + timeout
    rows = []
    while time.monotonic() < deadline:
        rows = [r for r in recent_flight_tasks(limit=500)
                if name in r["name"]]
        if len(rows) >= n:
            return rows
        time.sleep(0.25)
    return rows


def test_stage_stamps_monotonic_and_sum_to_total(rt_init):
    """Per-task transition stamps are monotonic and the four stage
    durations sum to within 10% of the end-to-end latency (they are
    equal by construction; the tolerance absorbs clamping)."""
    rt = rt_init

    @rt.remote
    def flightwork(x):
        time.sleep(0.005)
        return x * 2

    assert rt.get([flightwork.remote(i) for i in range(12)],
                  timeout=120) == [i * 2 for i in range(12)]

    from ray_tpu.observability.state import list_tasks

    rows = list_tasks(filters={"name": "flightwork", "state": "DONE"})
    assert len(rows) == 12
    for row in rows:
        ts = row["state_ts"]
        assert ts is not None, row
        order = [ts["submitted"], ts["queued"], ts["scheduled"],
                 ts["dispatched"], ts["finished"]]
        assert order == sorted(order), ts

    joined = _wait_joined("flightwork", 12)
    assert len(joined) >= 12, "exec deltas never joined head-side"
    for r in joined:
        assert r["exec_s"] > 0, r  # the sleep(0.005) must be visible
        stage_sum = (r["queue_s"] + r["sched_s"] + r["exec_s"]
                     + r["transfer_s"])
        assert stage_sum == pytest.approx(r["total_s"], rel=0.10), r


def test_summary_aggregates_and_cli(rt_init, capsys):
    """flight_summary() exposes per-stage count/p50/p99 per function;
    ``rt summary tasks`` renders it, ``rt list tasks --state`` and
    dotted ``--filter`` narrow the task table."""
    rt = rt_init

    @rt.remote
    def agg(x):
        return x + 1

    rt.get([agg.remote(i) for i in range(8)], timeout=120)
    assert len(_wait_joined("agg", 8)) >= 8

    from ray_tpu.observability import flight_summary, format_flight_summary

    summ = flight_summary()
    row = next(v for k, v in summ.items() if "agg" in k)
    assert row["count"] >= 8
    for stage in ("queue", "sched", "exec", "transfer", "total"):
        st = row["stages"][stage]
        assert st["count"] >= 8
        assert st["p99_ms"] >= st["p50_ms"] >= 0
    assert "agg" in format_flight_summary()

    from ray_tpu.scripts import cli

    assert cli.main(["summary", "tasks", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert any("agg" in k for k in data)

    assert cli.main(["list", "tasks", "--state", "DONE"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows and all(r["state"] == "DONE" for r in rows)
    # Dotted-path filter reaches nested fields (satellite 2).
    assert cli.main(["list", "tasks", "--filter",
                     "resources.CPU=1.0"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows and all(r["resources"]["CPU"] == 1.0 for r in rows)
    # Filters reject non-task entities with a usage error, not silence.
    assert cli.main(["list", "nodes", "--state", "DONE"]) == 2


def test_rt_logs_tails_worker_output(rt_init, capsys):
    """``rt logs`` dumps captured worker stdout/stderr with worker-id
    prefixes (satellite 3; non-follow path)."""
    rt = rt_init

    @rt.remote
    def chatty():
        print("flight logs probe line")
        return 1

    assert rt.get(chatty.remote()) == 1

    import os

    from ray_tpu.core.runtime import get_head_runtime
    from ray_tpu.scripts import cli

    log_dir = get_head_runtime().session_log_dir
    assert log_dir, "worker log capture should be on by default"
    # Wait for the redirected line to land in a worker log file, then
    # let the monitor's async driver echo drain so the capsys read
    # below sees ONLY what `rt logs` itself printed.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any("flight logs probe line" in open(
                os.path.join(log_dir, name)).read()
               for name in os.listdir(log_dir)
               if name.startswith("worker-") and name.endswith(".out")):
            break
        time.sleep(0.1)
    time.sleep(1.0)
    capsys.readouterr()
    assert cli.main(["logs"]) == 0
    out = capsys.readouterr().out
    assert "flight logs probe line" in out
    assert "(worker=" in out
    # --worker with a non-matching prefix filters everything out.
    assert cli.main(["logs", "--worker", "zzzzzzzz"]) == 0
    assert "flight logs probe line" not in capsys.readouterr().out


def test_dropped_counter_and_warn_once(caplog):
    """Bounded telemetry buffers count drops in
    rt_telemetry_dropped_total{buffer} and warn once per buffer
    (satellite 1)."""
    from ray_tpu.observability import telemetry
    from ray_tpu.observability.metrics import registry

    def total(buffer):
        ctr = registry.get("rt_telemetry_dropped_total")
        if ctr is None:
            return 0.0
        return sum(v for k, v in ctr.collect()[1].items()
                   if ("buffer", buffer) in k)

    exp = telemetry.TelemetryExporter(proc="droptest")
    for _ in range(telemetry._FLIGHT_BUF_MAX):
        exp._flight.append(("00", 0.0))
    before = total("flight_exporter")
    exp.record_flight("aa", 0.001)
    exp.record_flight("bb", 0.001)
    assert total("flight_exporter") == before + 2

    with caplog.at_level(logging.WARNING,
                         logger="ray_tpu.observability.telemetry"):
        telemetry.count_dropped("flight_test_unique")
        telemetry.count_dropped("flight_test_unique")
    warns = [r for r in caplog.records
             if "flight_test_unique" in r.getMessage()]
    assert len(warns) == 1, "must warn exactly once per buffer"
    assert total("flight_test_unique") == 2


def test_clean_store_on_reinit():
    """A new runtime in the same process (shutdown -> init, the
    in-process half of head replacement) starts with an EMPTY flight
    store — stale aggregates from the previous runtime's tasks must not
    leak into the new head's summary (satellite 4)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=2)
    try:
        @rt.remote
        def stale(x):
            return x

        rt.get([stale.remote(i) for i in range(4)], timeout=120)
        from ray_tpu.observability import flight_summary

        deadline = time.monotonic() + 25
        while time.monotonic() < deadline:
            if any("stale" in k for k in flight_summary()):
                break
            time.sleep(0.25)
        assert any("stale" in k for k in flight_summary())
    finally:
        rt.shutdown()
    rt.init(num_cpus=2)
    try:
        assert flight_summary() == {}, \
            "replacement runtime inherited the old flight store"
    finally:
        rt.shutdown()


# Driver script for the failover cycle: the PR-14 named-actor recovery
# workload plus a flight-recorded task batch; READY reports how many
# tasks joined exec deltas and that the summary renders.
_SRC_FLIGHT = r"""
import time
import ray_tpu as rt
from ray_tpu.observability import (flight_summary, format_flight_summary,
                                   recent_flight_tasks)

rt.init(num_cpus=2)


@rt.remote
def fwork(x):
    return x * 2


@rt.remote
class Counter:
    def bump(self):
        return 1


try:
    h = rt.get_actor("survivor")
    created = 0
except ValueError:
    h = Counter.options(name="survivor", max_restarts=5).remote()
    created = 1
v = rt.get(h.bump.remote(), timeout=120)
assert rt.get([fwork.remote(i) for i in range(8)], timeout=120) == \
    [i * 2 for i in range(8)]
deadline = time.time() + 20
joined = 0
while time.time() < deadline:
    joined = sum(1 for r in recent_flight_tasks(limit=500)
                 if "fwork" in r["name"])
    if joined >= 8:
        break
    time.sleep(0.25)
summ = flight_summary()
fn_row = next((v2 for k, v2 in summ.items() if "fwork" in k), None)
exec_count = (fn_row or {}).get("stages", {}).get("exec",
                                                  {}).get("count", 0)
table_ok = int("fwork" in format_flight_summary())
print("HEADKILLER_READY value=%d created=%d joined=%d exec_count=%d "
      "table_ok=%d" % (v, created, joined, exec_count, table_ok),
      flush=True)
while True:
    rt.get(h.bump.remote())
    time.sleep(0.005)
"""


@pytest.mark.chaos
@pytest.mark.skipif(not build_native(),
                    reason="native toolchain unavailable")
def test_flight_survives_head_failover(tmp_path):
    """SIGKILL the head mid-workload; the replacement head's flight
    recorder starts clean and records ONLY its own tasks — exec joins
    work and ``rt summary`` renders on the replacement too."""
    from ray_tpu.cluster_utils import HeadKiller

    killer = HeadKiller(str(tmp_path / "gcs.wal"), kill_after_s=0.3,
                        head_src=_SRC_FLIGHT)
    first = killer.run_cycle()
    assert first["created"] == 1
    assert first["joined"] == 8, first
    assert first["table_ok"] == 1, first

    second = killer.run_cycle()  # replacement head on the same WAL
    assert second["created"] == 0, "named actor must re-resolve"
    # Clean store: exactly THIS head's 8 tasks, nothing inherited.
    assert second["joined"] == 8, second
    assert second["exec_count"] == 8, second
    assert second["table_ok"] == 1, second
