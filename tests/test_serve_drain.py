"""Graceful drain + sticky-session routing tests (ISSUE 19): a drained
replica finishes its in-flight requests AND handed-off streams before
dying (zero drops, zero drain-caused errors), sessions stay pinned to
one replica and re-pin deterministically when it leaves the set."""

import threading
import time

import pytest

pytestmark = pytest.mark.chaos


@pytest.fixture()
def serve_instance(rt_shared):
    from ray_tpu import serve

    serve.start(http_port=18641)
    yield serve
    serve.shutdown()


def _replica_hexes(name):
    from ray_tpu.core import get
    from ray_tpu.serve.api import _controller

    return [r._actor_id.hex()
            for r in get(_controller().get_replicas.remote(name),
                         timeout=10)]


def test_sticky_session_routing_and_repin(serve_instance):
    """Requests tagged with one session id land on ONE replica; when
    that replica is drained the session re-pins (rendezvous hash) to a
    survivor and keeps being served."""
    serve = serve_instance
    from ray_tpu.core import get

    @serve.deployment(name="pinme", num_replicas=2,
                      health_check_period_s=0.25)
    def pinme(_=None):
        import os as _os

        return _os.getpid()

    handle = serve.run(pinme.bind())
    sess = handle.session("alpha")
    pids = {get(sess.remote(), timeout=30) for _ in range(6)}
    assert len(pids) == 1  # pinned
    pinned_key = handle._router.session_replica("alpha")
    assert pinned_key is not None

    rep = serve.drain("pinme", replica=pinned_key, timeout_s=20.0)
    assert rep.get("error") is None, rep
    # Session must re-pin to a live replica and keep serving.
    pid2 = get(sess.remote(), timeout=30)
    assert pid2 not in pids
    assert handle._router.session_replica("alpha") != pinned_key


def test_drain_completes_streams_not_severed(serve_instance):
    """Regression (satellite): drain must NOT sever in-progress
    streams. A stream being consumed while its replica drains completes
    normally — no StreamInterruptedError — and a replacement replica
    appears."""
    serve = serve_instance

    @serve.deployment(name="drainstream", num_replicas=1,
                      health_check_period_s=0.25)
    def streamer(n=12):
        import os as _os
        import time as _time

        count = int(n) if not isinstance(n, dict) else 12

        def gen():
            yield _os.getpid()
            for i in range(count):
                _time.sleep(0.08)
                yield i

        return gen()

    handle = serve.run(streamer.bind())
    before = set(_replica_hexes("drainstream"))
    assert len(before) == 1
    it = iter(handle.stream(12))
    pid = next(it)  # stream is live on the (sole) replica
    assert isinstance(pid, int)

    drain_result = {}

    def do_drain():
        drain_result.update(
            serve.drain("drainstream", timeout_s=30.0))

    t = threading.Thread(target=do_drain)
    t.start()
    got = list(it)  # must complete, not raise StreamInterruptedError
    t.join(timeout=60)
    assert got == list(range(12))
    assert drain_result.get("error") is None, drain_result
    assert drain_result.get("timed_out") is False, drain_result
    # Reconciliation replaced the drained replica.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        after = set(_replica_hexes("drainstream"))
        if after and not (after & before):
            break
        time.sleep(0.2)
    assert after and not (after & before)


def test_drain_zero_dropped_requests(serve_instance):
    """Requests in flight on the draining replica (and requests racing
    the drain) all complete — no drops, no drain-caused errors."""
    serve = serve_instance
    from ray_tpu.core import get

    @serve.deployment(name="drainbusy", num_replicas=2,
                      health_check_period_s=0.25)
    def busy(_=None):
        import time as _time

        _time.sleep(0.15)
        return 1

    handle = serve.run(busy.bind())
    assert get(handle.remote(), timeout=30) == 1
    results, errors = [], []
    lock = threading.Lock()

    def call():
        try:
            r = get(handle.remote(), timeout=60)
            with lock:
                results.append(r)
        except Exception as e:  # noqa: BLE001 — counted, not raised
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=call) for _ in range(10)]
    for th in threads:
        th.start()
    time.sleep(0.05)  # let a few land in flight
    rep = serve.drain("drainbusy", timeout_s=30.0)
    for th in threads:
        th.join(timeout=90)
    assert rep.get("error") is None, rep
    assert errors == [], errors
    assert results == [1] * 10
