"""RayCluster operator tests (reference: KubeRay raycluster_controller
reconcile behavior + the kuberay node provider)."""

import pytest

from ray_tpu.autoscaler.kube_operator import (
    KubeRayNodeProvider,
    KubectlAPI,
    MockKubeAPI,
    Pod,
    RayClusterOperator,
    RayClusterSpec,
    WorkerGroupSpec,
)


def _spec(replicas=2):
    return RayClusterSpec(
        name="demo",
        head_resources={"CPU": 2.0},
        worker_groups=[WorkerGroupSpec("cpu", replicas=replicas,
                                       min_replicas=0, max_replicas=4,
                                       resources={"CPU": 4.0})],
    )


def test_crd_parse_from_dict():
    doc = {
        "apiVersion": "ray.io/v1",
        "kind": "RayCluster",
        "metadata": {"name": "parsed"},
        "spec": {
            "headGroupSpec": {"resources": {"CPU": 2}},
            "workerGroupSpecs": [
                {"groupName": "cpu", "replicas": 3, "minReplicas": 1,
                 "maxReplicas": 8, "resources": {"CPU": 4}},
                {"groupName": "tpu", "replicas": 1,
                 "resources": {"TPU": 8}},
            ],
        },
    }
    spec = RayClusterSpec.from_dict(doc)
    assert spec.name == "parsed"
    assert [g.group_name for g in spec.worker_groups] == ["cpu", "tpu"]
    assert spec.worker_groups[0].max_replicas == 8
    with pytest.raises(ValueError, match="RayCluster"):
        RayClusterSpec.from_dict({"kind": "Deployment"})


def test_reconcile_converges_to_spec():
    api = MockKubeAPI(ready_after=1)
    op = RayClusterOperator(api, _spec(replicas=2))
    st = op.reconcile()
    assert st["num_pods"] == 3  # 1 head + 2 workers
    assert st["state"] == "reconciling"  # pods still Pending
    op.reconcile()
    st = op.reconcile()
    assert st["state"] == "ready"
    assert st["head"]["ready"]
    assert st["worker_groups"]["cpu"]["ready"] == 2
    # Idempotent: nothing new appears.
    assert op.reconcile()["num_pods"] == 3


def test_head_crash_replaced():
    api = MockKubeAPI()
    op = RayClusterOperator(api, _spec(replicas=1))
    op.reconcile()
    head = [p for p in api.list_pods({"ray.io/role": "head"})][0]
    api.fail_pod(head.name)
    op.reconcile()  # deletes the failed head
    st = op.reconcile()  # recreates
    heads = api.list_pods({"ray.io/role": "head"})
    assert len(heads) == 1 and heads[0].name != head.name
    assert st["head"]["ready"]


def test_scale_up_down_clamped():
    api = MockKubeAPI()
    op = RayClusterOperator(api, _spec(replicas=2))
    op.reconcile()
    op.scale_group("cpu", 9)  # clamped to max_replicas=4
    st = op.reconcile()
    assert st["worker_groups"]["cpu"]["ready"] + \
        st["worker_groups"]["cpu"]["pending"] == 4
    op.scale_group("cpu", 1)
    op.reconcile()
    pods = [p for p in api.list_pods({"ray.io/cluster": "demo"})
            if p.role == "worker"]
    assert len(pods) == 1
    with pytest.raises(KeyError):
        op.scale_group("nope", 1)


def test_failed_worker_replaced_preserving_count():
    api = MockKubeAPI()
    op = RayClusterOperator(api, _spec(replicas=2))
    op.reconcile()
    victim = [p for p in api.list_pods({"ray.io/role": "worker"})][0]
    api.fail_pod(victim.name)
    op.reconcile()
    op.reconcile()
    workers = api.list_pods({"ray.io/role": "worker"})
    assert len(workers) == 2
    assert victim.name not in {p.name for p in workers}


def test_autoscaler_drives_replicas_through_operator():
    """StandardAutoscaler scales a worker group by editing the CRD —
    the KubeRay arrangement, operator owns the pods."""
    from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig,
                                               LoadMetrics,
                                               StandardAutoscaler)
    from ray_tpu.autoscaler.autoscaler import NodeType

    api = MockKubeAPI()
    op = RayClusterOperator(api, _spec(replicas=0))
    op.reconcile()
    provider = KubeRayNodeProvider(op)
    cfg = AutoscalerConfig(node_types={
        "cpu": NodeType(name="cpu", resources={"CPU": 4.0},
                        max_workers=4),
    })
    autoscaler = StandardAutoscaler(provider, cfg)
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"CPU": 4.0}] * 2)
    autoscaler.update(metrics)
    op.reconcile()
    st = op.status()
    assert st["worker_groups"]["cpu"]["ready"] + \
        st["worker_groups"]["cpu"]["pending"] == 2
    # Demand gone + idle: autoscaler terminates through the provider.
    metrics.set_pending_demands([])
    for p in api.list_pods({"ray.io/role": "worker"}):
        metrics.update_node(p.name, {"CPU": 4.0}, {"CPU": 4.0})
    autoscaler.update(metrics)


def test_kubectl_api_gated():
    import shutil

    if shutil.which("kubectl") is None:
        with pytest.raises(RuntimeError, match="kubectl"):
            KubectlAPI()


def test_background_loop_converges():
    import time

    api = MockKubeAPI(ready_after=0)
    op = RayClusterOperator(api, _spec(replicas=2),
                            poll_interval_s=0.05).run()
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if op.status()["state"] == "ready":
                break
            time.sleep(0.05)
        assert op.status()["state"] == "ready"
    finally:
        op.stop()
