"""Scalability-envelope regression tests (scaled-down bench_envelope):
the control plane must survive a task flood without missing heartbeats,
and shared-process actors must reach fleet scale quickly.

Reference analog: release/benchmarks/distributed/test_many_tasks.py —
"no node dies while the head is saturated" is the property the release
envelope actually guards."""

import time

import pytest


@pytest.fixture()
def flood_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    # Two real daemon-process nodes whose liveness rides heartbeats
    # over TCP — the thing a GIL-saturated head could starve.
    for _ in range(2):
        cluster.add_node(num_cpus=1, remote=True)
    cluster.wait_for_nodes(timeout=120)
    yield cluster
    cluster.shutdown()


def test_heartbeats_survive_task_flood(flood_cluster):
    import ray_tpu as rt
    from ray_tpu.observability.state import list_nodes

    @rt.remote
    def noop():
        return None

    assert all(n["alive"] for n in list_nodes())
    n_tasks = 8_000
    refs = [noop.remote() for _ in range(n_tasks)]
    # Poll liveness DURING the drain, not just after: a missed
    # heartbeat marks the node dead immediately and a later poll could
    # race a (hypothetical) recovery path.
    deadline = time.time() + 600
    pending = list(refs)
    while pending and time.time() < deadline:
        done, pending = rt.wait(pending,
                                num_returns=min(2000, len(pending)),
                                timeout=30)
        nodes = list_nodes()
        dead = [n["node_id"] for n in nodes if not n["alive"]]
        assert not dead, (
            f"nodes {dead} marked dead mid-flood — heartbeats starved")
    assert not pending, "flood did not drain in time"
    assert all(n["alive"] for n in list_nodes())


def test_thousand_shared_actors_alive():
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=2)

    @rt.remote(shared_process=True)
    class Holder:
        def __init__(self, i):
            self.i = i

        def whoami(self):
            return self.i

    n = 300  # CI-scale; bench_envelope runs the full 1000
    t0 = time.perf_counter()
    actors = [Holder.remote(i) for i in range(n)]
    got = rt.get([a.whoami.remote() for a in actors], timeout=600)
    dt = time.perf_counter() - t0
    assert got == list(range(n))
    assert dt < 120, f"{n} shared actors took {dt:.0f}s"
    for a in actors:
        rt.kill(a)
    rt.shutdown()
