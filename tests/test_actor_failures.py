"""Actor fault-tolerance tests (fresh runtime per test).

Mirrors reference coverage in ``python/ray/tests/test_actor_failures.py``.
"""

import time

import pytest


def test_kill_actor(rt_init):
    rt = rt_init

    @rt.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert rt.get(v.ping.remote()) == "pong"
    rt.kill(v)
    with pytest.raises(rt.ActorError):
        rt.get(v.ping.remote(), timeout=15)


def test_actor_restart(rt_init):
    rt = rt_init

    @rt.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    assert rt.get(p.incr.remote()) == 1
    p.die.remote()
    # After restart state is fresh (recovered via user checkpointing if
    # needed, like the reference).
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            result = rt.get(p.incr.remote(), timeout=10)
            break
        except rt.ActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")
    assert result == 1


def test_actor_no_restart_fails_calls(rt_init):
    rt = rt_init

    @rt.remote
    class Mortal:
        def die(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    m = Mortal.remote()
    assert rt.get(m.ping.remote()) == "pong"
    m.die.remote()
    with pytest.raises(rt.ActorError):
        rt.get(m.ping.remote(), timeout=15)

