"""DAG + Workflow tests (mirrors python/ray/dag and ray/workflow tests)."""

import os

import pytest


def test_function_dag(rt_shared):
    import ray_tpu as rt
    from ray_tpu.dag import InputNode

    @rt.remote
    def add(a, b):
        return a + b

    @rt.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 5)

    assert rt.get(dag.execute(10)) == 25


def test_class_dag(rt_shared):
    import ray_tpu as rt
    from ray_tpu.dag import InputNode

    @rt.remote
    class Adder:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    with InputNode() as inp:
        node = Adder.bind(100)
        dag = node.add.bind(inp)

    assert rt.get(dag.execute(7)) == 107


def test_diamond_dag(rt_shared):
    import ray_tpu as rt
    from ray_tpu.dag import InputNode

    @rt.remote
    def left(x):
        return x + 1

    @rt.remote
    def right(x):
        return x * 2

    @rt.remote
    def join(a, b):
        return (a, b)

    with InputNode() as inp:
        dag = join.bind(left.bind(inp), right.bind(inp))

    assert rt.get(dag.execute(10)) == (11, 20)


def test_workflow_run_and_output(rt_shared, tmp_path):
    import ray_tpu as rt
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path))

    @rt.remote
    def step_a(x):
        return x + 1

    @rt.remote
    def step_b(x):
        return x * 10

    with InputNode() as inp:
        dag = step_b.bind(step_a.bind(inp))

    result = workflow.run(dag, workflow_id="wf-test", workflow_input=4)
    assert result == 50
    assert workflow.get_status("wf-test") == "SUCCESSFUL"
    assert workflow.get_output("wf-test") == 50


def test_workflow_resume_skips_completed_steps(rt_shared, tmp_path):
    import ray_tpu as rt
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path))
    marker = str(tmp_path / "fail_once")

    @rt.remote
    def expensive(x):
        # Count executions via a side file.
        count_file = str(tmp_path) + "/exec_count"
        n = int(open(count_file).read()) if os.path.exists(count_file) else 0
        open(count_file, "w").write(str(n + 1))
        return x * 2

    @rt.remote
    def flaky(x):
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            raise RuntimeError("transient failure")
        return x + 1

    with InputNode() as inp:
        dag = flaky.bind(expensive.bind(inp))

    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf-resume", workflow_input=3)
    assert workflow.get_status("wf-resume") == "FAILED"

    result = workflow.resume("wf-resume")
    assert result == 7
    # expensive ran only ONCE: the resume used its persisted result.
    assert open(str(tmp_path) + "/exec_count").read() == "1"
    assert workflow.get_status("wf-resume") == "SUCCESSFUL"


def test_workflow_list(rt_shared, tmp_path):
    import ray_tpu as rt
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path))

    @rt.remote
    def f(x):
        return x

    with InputNode() as inp:
        dag = f.bind(inp)

    workflow.run(dag, workflow_id="wf-1", workflow_input=1)
    rows = workflow.list_all()
    assert any(r["workflow_id"] == "wf-1" and r["status"] == "SUCCESSFUL"
               for r in rows)


def test_workflow_step_retries_and_catch(rt_init, tmp_path):
    """Per-step options: max_retries re-runs flaky steps; catch_exceptions
    converts failures to (None, exc) results (reference: workflow step
    options + api)."""
    import ray_tpu as rt
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf"))
    marker = tmp_path / "attempts.txt"

    @rt.remote
    def flaky(x):
        with open(marker, "a") as f:
            f.write("x")
        n = len(open(marker).read())
        if n < 3:
            raise RuntimeError(f"attempt {n} fails")
        return x * 10

    node = workflow.options(flaky.bind(7), max_retries=5,
                            retry_delay_s=0.01)
    assert workflow.run(node, workflow_id="wf-retry") == 70
    events = workflow.get_events("wf-retry")
    kinds = [e["event"] for e in events]
    assert kinds.count("step_failed") == 2
    assert "step_finished" in kinds

    @rt.remote
    def always_boom():
        raise ValueError("nope")

    caught = workflow.options(always_boom.bind(), catch_exceptions=True)
    value, err = workflow.run(caught, workflow_id="wf-catch")
    assert value is None and isinstance(err, Exception)
    assert workflow.get_status("wf-catch") == "SUCCESSFUL"


def test_workflow_continuation_and_event(rt_init, tmp_path):
    """A step returning a DAG continues into it (sub-workflow), and
    wait_for_event steps persist their event payload."""
    import ray_tpu as rt
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf2"))

    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def make_continuation(x):
        # Returns a DAG: the workflow continues into double(x + 1).
        return double.bind(x + 1)

    out = workflow.run(make_continuation.bind(10), workflow_id="wf-cont")
    assert out == 22

    class Ready(workflow.EventListener):
        def __init__(self, payload):
            self._payload = payload

        def poll_for_event(self):
            return {"event_payload": self._payload}

    ev = workflow.wait_for_event(Ready, "go")
    result = workflow.run(ev, workflow_id="wf-event")
    assert result == {"event_payload": "go"}
    # Resume must NOT re-wait: the persisted event result is reused.
    assert workflow.resume("wf-event") == {"event_payload": "go"}
