"""ExternalEnv + PolicyClient/PolicyServerInput tests
(reference: rllib/tests/test_external_env.py, test_policy_client_server_*)."""

import threading

import numpy as np
import pytest

from ray_tpu.rllib.env import FastCartPole
from ray_tpu.rllib.external import (
    ExternalDQNWorker,
    ExternalEnv,
    ExternalEnvWorker,
    PolicyClient,
    PolicyServerInput,
)
from ray_tpu.rllib.sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


class CartPoleExternal(ExternalEnv):
    """A simulator that OWNS the loop and queries the policy
    (the reference's canonical ExternalEnv example)."""

    def __init__(self, episodes: int = 50, off_policy_every: int = 0):
        super().__init__(obs_shape=(4,), num_actions=2)
        self._episodes_to_run = episodes
        self._off_policy_every = off_policy_every
        self._sim = FastCartPole(num_envs=1, seed=7)

    def run(self):
        for i in range(self._episodes_to_run):
            eid = self.start_episode()
            obs = self._sim.vector_reset()[0]
            done, steps = False, 0
            while not done and steps < 200:
                if self._off_policy_every and steps % self._off_policy_every == 1:
                    action = 0
                    self.log_action(eid, obs, action)
                else:
                    action = self.get_action(eid, obs)
                nobs, rew, dones, _ = self._sim.vector_step(
                    np.array([action]))
                self.log_returns(eid, float(rew[0]))
                obs, done = nobs[0], bool(dones[0])
                steps += 1
            self.end_episode(eid, obs)


def test_external_env_worker_collects_coherent_transitions():
    worker = ExternalEnvWorker(lambda: CartPoleExternal(episodes=200))
    batch = worker.sample(rollout_length=64)
    n = len(batch[OBS])
    assert n >= 64
    assert batch[OBS].shape == (n, 4)
    assert batch[NEXT_OBS].shape == (n, 4)
    assert batch[ACTIONS].shape == (n,)
    assert set(np.unique(batch[ACTIONS])) <= {0, 1}
    # Rewards are 1.0 per surviving step in FastCartPole.
    assert np.all(batch[REWARDS] >= 0.0)
    # Within an episode the rows chain: next_obs[t] == obs[t+1].
    for t in range(n - 1):
        if not batch[DONES][t]:
            np.testing.assert_allclose(batch[NEXT_OBS][t],
                                       batch[OBS][t + 1], rtol=1e-5)
    stats = worker.episode_stats()
    # Some episodes should have completed during sampling or at least
    # rewards should be accumulating.
    assert stats["episodes"] >= 0


def test_external_env_off_policy_log_action():
    worker = ExternalEnvWorker(
        lambda: CartPoleExternal(episodes=100, off_policy_every=3))
    batch = worker.sample(rollout_length=48)
    # Off-policy rows (forced action 0) are interleaved with on-policy
    # ones; the batch contains both and stays coherent.
    assert len(batch[OBS]) >= 48


def test_external_env_episode_errors():
    env = CartPoleExternal(episodes=1)
    eid = env.start_episode("ep1")
    with pytest.raises(ValueError):
        env.start_episode("ep1")  # duplicate
    env.log_returns("ep1", 1.0)
    env.end_episode("ep1", np.zeros(4))
    with pytest.raises(ValueError):
        env.log_returns("ep1", 1.0)  # finished
    with pytest.raises(ValueError):
        env.get_action("nope", np.zeros(4))


def test_policy_server_client_round_trip():
    server = PolicyServerInput(obs_shape=(4,), num_actions=2, port=0)
    worker = ExternalDQNWorker(server)
    worker.set_epsilon(0.3)
    client = PolicyClient(server.address)
    sim = FastCartPole(num_envs=1, seed=3)

    client_done = threading.Event()
    failures = []

    def drive():
        try:
            for _ in range(30):
                eid = client.start_episode()
                obs = sim.vector_reset()[0]
                done, steps = False, 0
                while not done and steps < 100:
                    a = client.get_action(eid, obs)
                    assert a in (0, 1)
                    nobs, rew, dones, _ = sim.vector_step(np.array([a]))
                    client.log_returns(eid, float(rew[0]))
                    obs, done = nobs[0], bool(dones[0])
                    steps += 1
                client.end_episode(eid, obs)
        except Exception as e:  # noqa: BLE001
            failures.append(e)
        finally:
            client_done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    batch = worker.sample(rollout_length=64)
    assert len(batch[OBS]) >= 64
    assert batch[DONES].dtype == bool
    # Let the client finish cleanly by pumping any stragglers.
    while not client_done.is_set():
        try:
            worker.sample(rollout_length=8, timeout_s=2.0)
        except TimeoutError:
            pass
    assert not failures, failures
    server.shutdown()


def test_policy_client_error_propagates():
    server = PolicyServerInput(obs_shape=(4,), num_actions=2, port=0)
    ExternalEnvWorker(server)  # starts the serving thread
    client = PolicyClient(server.address)
    with pytest.raises(RuntimeError, match="not found"):
        client.log_returns("missing-episode", 1.0)
    server.shutdown()
