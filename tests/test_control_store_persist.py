"""Control-store persistence: mutation log replay across daemon restarts.

Reference coverage analog: gcs_table_storage/redis persistence tests —
GCS restart recovers node/KV state.
"""

import pytest

from ray_tpu.core.gcs_socket import (
    ControlStoreError,
    ControlStoreProcess,
    build_native,
)

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable")


def test_kv_and_nodes_survive_restart(tmp_path):
    log = str(tmp_path / "gcs.log")

    proc = ControlStoreProcess(persist_path=log)
    c = proc.client()
    c.kv_put(b"durable", b"v1")
    c.kv_put(b"temp", b"x")
    c.kv_del(b"temp")
    c.kv_put(b"ns-key", b"nsv", namespace="other")
    c.register_node(b"node-a", b"info-a")
    c.register_node(b"node-b", b"info-b")
    c.mark_node_dead(b"node-b")
    c.close()
    proc.stop()

    proc2 = ControlStoreProcess(persist_path=log)
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"durable") == b"v1"
        assert c2.kv_get(b"temp") is None
        assert c2.kv_get(b"ns-key", namespace="other") == b"nsv"
        nodes = {n["node_id"]: n for n in c2.list_nodes()}
        assert nodes[b"node-a"]["alive"]
        assert nodes[b"node-a"]["info"] == b"info-a"
        assert not nodes[b"node-b"]["alive"]
        # New mutations keep appending to the same log.
        c2.kv_put(b"second-life", b"v2")
    finally:
        c2.close()
        proc2.stop()

    proc3 = ControlStoreProcess(persist_path=log)
    c3 = proc3.client()
    try:
        assert c3.kv_get(b"durable") == b"v1"
        assert c3.kv_get(b"second-life") == b"v2"
    finally:
        c3.close()
        proc3.stop()


def test_no_overwrite_semantics_replay(tmp_path):
    log = str(tmp_path / "gcs.log")
    proc = ControlStoreProcess(persist_path=log)
    c = proc.client()
    assert c.kv_put(b"first", b"a", overwrite=False)
    assert not c.kv_put(b"first", b"b", overwrite=False)
    c.close()
    proc.stop()

    proc2 = ControlStoreProcess(persist_path=log)
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"first") == b"a"  # replay preserves first-wins
    finally:
        c2.close()
        proc2.stop()


def test_torn_tail_tolerated(tmp_path):
    log = tmp_path / "gcs.log"
    proc = ControlStoreProcess(persist_path=str(log))
    c = proc.client()
    c.kv_put(b"whole", b"record")
    c.close()
    proc.stop()

    # Simulate a crash mid-append: garbage half-record at the tail.
    with open(log, "ab") as f:
        f.write(b"\xff\xff\xff")

    proc2 = ControlStoreProcess(persist_path=str(log))
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"whole") == b"record"
    finally:
        c2.close()
        proc2.stop()


def test_torn_tail_truncated_so_future_appends_replay(tmp_path):
    """SIGKILL mid-append leaves a byte-chopped final record. Replay
    must DROP it (skip on recovery) and truncate the log — otherwise
    post-restart appends land after the garbage and every future replay
    silently loses them."""
    log = tmp_path / "gcs.log"
    proc = ControlStoreProcess(persist_path=str(log))
    c = proc.client()
    c.kv_put(b"k1", b"v1")
    c.kv_put(b"k2", b"x" * 256)  # the record the "crash" tears
    c.close()
    proc.stop()

    size = log.stat().st_size
    with open(log, "rb+") as f:
        f.truncate(size - 100)  # chop into the middle of the k2 record

    proc2 = ControlStoreProcess(persist_path=str(log))
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"k1") == b"v1"
        assert c2.kv_get(b"k2") is None  # torn record skipped, not fatal
        c2.kv_put(b"k3", b"v3")  # appends after the truncated tail
    finally:
        c2.close()
        proc2.stop()

    proc3 = ControlStoreProcess(persist_path=str(log))
    c3 = proc3.client()
    try:
        assert c3.kv_get(b"k1") == b"v1"
        assert c3.kv_get(b"k3") == b"v3", \
            "post-crash mutations must survive the NEXT restart"
        assert c3.kv_get(b"k2") is None
    finally:
        c3.close()
        proc3.stop()


def test_tables_survive_restart(tmp_path):
    """Durable FSM tables (actor/job/PG records): put/del/scan round-trip
    the WAL across daemon restarts."""
    log = str(tmp_path / "gcs.log")
    proc = ControlStoreProcess(persist_path=log)
    c = proc.client()
    c.table_put("actors", b"a1", b"rec1")
    c.table_put("actors", b"a2", b"rec2")
    c.table_put("actors", b"a1", b"rec1b")  # overwrite wins
    c.table_put("jobs", b"j1", b"jrec")
    c.table_del("actors", b"a2")
    assert dict(c.table_scan("actors")) == {b"a1": b"rec1b"}
    c.close()
    proc.stop()

    proc2 = ControlStoreProcess(persist_path=log)
    c2 = proc2.client()
    try:
        assert dict(c2.table_scan("actors")) == {b"a1": b"rec1b"}
        assert dict(c2.table_scan("jobs")) == {b"j1": b"jrec"}
        assert c2.table_scan("nope") == []
    finally:
        c2.close()
        proc2.stop()


def test_client_reconnects_after_store_restart(tmp_path):
    """Satellite: a live client rides out a daemon restart — the next
    call reconnects with bounded backoff instead of failing."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    log = str(tmp_path / "gcs.log")
    proc = ControlStoreProcess(port=port, persist_path=log)
    c = proc.client()
    c.kv_put(b"k", b"v")
    proc._proc.kill()  # hard daemon crash, client conn left dangling
    proc._proc.wait()

    proc2 = ControlStoreProcess(port=port, persist_path=log)
    try:
        assert c.kv_get(b"k") == b"v"  # transparent reconnect + replayed KV
        assert c.ping()
    finally:
        c.close()
        proc2.stop()


def test_subscriber_resubscribes_after_store_restart(tmp_path):
    """The dedicated subscription connection also heals: after a daemon
    restart it re-dials and re-issues its channel subscriptions, so
    pushes keep flowing instead of going silently dead."""
    import socket
    import time

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    proc = ControlStoreProcess(port=port)
    c = proc.client()
    received = []
    c.subscribe("CH", received.append)
    c.publish("CH", b"one")
    deadline = time.monotonic() + 10
    while b"one" not in received and time.monotonic() < deadline:
        time.sleep(0.02)
    assert b"one" in received

    proc._proc.kill()  # hard daemon crash
    proc._proc.wait()
    proc2 = ControlStoreProcess(port=port)
    try:
        # The reader thread reconnects+resubscribes on its own schedule;
        # keep publishing until a push lands on the healed subscription.
        # (publish itself is deliberately non-retryable — ping heals the
        # request connection first.)
        deadline = time.monotonic() + 15
        while b"two" not in received and time.monotonic() < deadline:
            try:
                c.ping()
                c.publish("CH", b"two")
            except (ControlStoreError, OSError):
                pass
            time.sleep(0.05)
        assert b"two" in received, "pushes must survive a store restart"
    finally:
        c.close()
        proc2.stop()
