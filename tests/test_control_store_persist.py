"""Control-store persistence: mutation log replay across daemon restarts.

Reference coverage analog: gcs_table_storage/redis persistence tests —
GCS restart recovers node/KV state.
"""

import pytest

from ray_tpu.core.gcs_socket import ControlStoreProcess, build_native

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable")


def test_kv_and_nodes_survive_restart(tmp_path):
    log = str(tmp_path / "gcs.log")

    proc = ControlStoreProcess(persist_path=log)
    c = proc.client()
    c.kv_put(b"durable", b"v1")
    c.kv_put(b"temp", b"x")
    c.kv_del(b"temp")
    c.kv_put(b"ns-key", b"nsv", namespace="other")
    c.register_node(b"node-a", b"info-a")
    c.register_node(b"node-b", b"info-b")
    c.mark_node_dead(b"node-b")
    c.close()
    proc.stop()

    proc2 = ControlStoreProcess(persist_path=log)
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"durable") == b"v1"
        assert c2.kv_get(b"temp") is None
        assert c2.kv_get(b"ns-key", namespace="other") == b"nsv"
        nodes = {n["node_id"]: n for n in c2.list_nodes()}
        assert nodes[b"node-a"]["alive"]
        assert nodes[b"node-a"]["info"] == b"info-a"
        assert not nodes[b"node-b"]["alive"]
        # New mutations keep appending to the same log.
        c2.kv_put(b"second-life", b"v2")
    finally:
        c2.close()
        proc2.stop()

    proc3 = ControlStoreProcess(persist_path=log)
    c3 = proc3.client()
    try:
        assert c3.kv_get(b"durable") == b"v1"
        assert c3.kv_get(b"second-life") == b"v2"
    finally:
        c3.close()
        proc3.stop()


def test_no_overwrite_semantics_replay(tmp_path):
    log = str(tmp_path / "gcs.log")
    proc = ControlStoreProcess(persist_path=log)
    c = proc.client()
    assert c.kv_put(b"first", b"a", overwrite=False)
    assert not c.kv_put(b"first", b"b", overwrite=False)
    c.close()
    proc.stop()

    proc2 = ControlStoreProcess(persist_path=log)
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"first") == b"a"  # replay preserves first-wins
    finally:
        c2.close()
        proc2.stop()


def test_torn_tail_tolerated(tmp_path):
    log = tmp_path / "gcs.log"
    proc = ControlStoreProcess(persist_path=str(log))
    c = proc.client()
    c.kv_put(b"whole", b"record")
    c.close()
    proc.stop()

    # Simulate a crash mid-append: garbage half-record at the tail.
    with open(log, "ab") as f:
        f.write(b"\xff\xff\xff")

    proc2 = ControlStoreProcess(persist_path=str(log))
    c2 = proc2.client()
    try:
        assert c2.kv_get(b"whole") == b"record"
    finally:
        c2.close()
        proc2.stop()
