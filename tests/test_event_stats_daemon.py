"""Daemon-process event stats aggregate to the head (own module:
standalone Cluster must not share a module with rt_shared fixtures)."""

import ray_tpu as rt


def test_daemon_event_stats_reach_head():
    """daemon.* handler rows from the daemon's OWN process aggregate
    into the head's event_loop_stats with a node column."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.observability import event_loop_stats

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        nid = cluster.add_node(num_cpus=2, resources={"zone_d": 1.0},
                               remote=True)
        cluster.wait_for_nodes()

        @rt.remote(resources={"zone_d": 0.1})
        def f(x):
            return x * 2

        assert rt.get([f.remote(i) for i in range(8)]) == \
            [2 * i for i in range(8)]
        rows = event_loop_stats(top=0)
        daemon_rows = [r for r in rows
                       if r["handler"].startswith("daemon.")]
        assert daemon_rows, [r["handler"] for r in rows][:10]
        assert all(r["node"] != "head" for r in daemon_rows)
        assert any(r["node"] == "head" for r in rows)
    finally:
        cluster.shutdown()


