"""SAC: continuous-action soft actor-critic.

Learning test pattern: reference ``rllib/utils/test_utils.py:511``
``check_learning_achieved`` — train for a bounded number of iterations
and require the reward threshold to be crossed.
"""

import numpy as np
import pytest

from ray_tpu.rllib import SAC, SACConfig
from ray_tpu.rllib.env import FastPendulum
from ray_tpu.rllib.sac import init_sac_params, sample_action


def test_pendulum_env_matches_gym_reward_shape():
    env = FastPendulum(num_envs=4, seed=0)
    obs = env.vector_reset(seed=0)
    assert obs.shape == (4, 3)
    # cos^2 + sin^2 == 1
    np.testing.assert_allclose(obs[:, 0] ** 2 + obs[:, 1] ** 2, 1.0,
                               rtol=1e-5)
    obs, rew, done, _ = env.vector_step(np.zeros((4, 1), np.float32))
    # reward is -(cost); cost >= 0 always
    assert (rew <= 0).all()
    assert not done.any()
    saw_done = False
    for _ in range(FastPendulum.MAX_STEPS):
        obs, rew, done, _ = env.vector_step(np.zeros((4, 1), np.float32))
        saw_done = saw_done or bool(done.any())
    assert saw_done  # time-limit reset fired


def test_squashed_gaussian_logp_matches_numeric():
    """logp from sample_action must integrate the tanh+affine change of
    variables: check against a numeric estimate via binning."""
    import jax

    key = jax.random.PRNGKey(0)
    params = init_sac_params(key, obs_dim=3, action_dim=1, hidden=(16,))
    obs = np.zeros((20000, 3), np.float32)
    a, logp = sample_action(params["actor"], obs, key, 1, -2.0, 2.0)
    a = np.asarray(a)[:, 0]
    logp = np.asarray(logp)
    assert a.min() >= -2.0 and a.max() <= 2.0
    # Monte-Carlo check: density of samples near the median action should
    # match exp(logp) there within sampling noise.
    lo, hi = np.percentile(a, 45), np.percentile(a, 55)
    frac = ((a >= lo) & (a <= hi)).mean()
    density = frac / max(hi - lo, 1e-9)
    in_bin = (a >= lo) & (a <= hi)
    mean_logp_density = float(np.exp(logp[in_bin]).mean())
    assert density == pytest.approx(mean_logp_density, rel=0.2)


def test_sac_smoke_one_iteration():
    config = (
        SACConfig()
        .environment("FastPendulum")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                  rollout_fragment_length=8)
        .training(train_batch_size=64, learning_starts=16,
                  num_updates_per_iter=2)
        .debugging(seed=0)
    )
    config.policy_hidden = (32, 32)
    algo = config.build()
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert r2["num_learner_updates"] > 0
        assert np.isfinite(r2["critic_loss"])
        assert np.isfinite(r2["actor_loss"])
        assert r2["alpha"] > 0
        # save/restore round-trip
        state = algo.get_state()
        algo.set_state(state)
    finally:
        algo.stop()


@pytest.mark.slow
def test_sac_pendulum_learns():
    config = (
        SACConfig()
        .environment("FastPendulum")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                  rollout_fragment_length=8)
        .training(lr=1e-3, train_batch_size=128, learning_starts=500,
                  num_updates_per_iter=32, tau=0.01)
        .debugging(seed=0)
    )
    config.policy_hidden = (64, 64)
    algo = config.build()
    best = -np.inf
    try:
        for _ in range(220):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if best >= -350.0:
                break
    finally:
        algo.stop()
    # Random policy: ~-1100..-1400. Learned: > -350 (good is ~-150).
    assert best >= -350.0, f"SAC did not learn pendulum (best={best:.0f})"
