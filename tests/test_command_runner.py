"""Autoscaler bring-up path (command runners + NodeUpdater) and the
push-based node-death broadcast.

Reference analogs: ``autoscaler/_private/command_runner.py`` +
``updater.py`` (a launched host is configured and joined by an updater),
and ``src/ray/common/ray_syncer/ray_syncer.h:88`` (state changes PUSH to
subscribers instead of interval polls).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    CommandRunnerError,
    FakeNodeProvider,
    LoadMetrics,
    NodeType,
    NodeUpdater,
    SSHCommandRunner,
    StandardAutoscaler,
    SubprocessCommandRunner,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_subprocess_runner_basics(tmp_path):
    r = SubprocessCommandRunner(cwd=str(tmp_path))
    assert r.run("echo hello").strip() == "hello"
    assert r.run("echo $MARKER", env={"MARKER": "x42"}).strip() == "x42"
    with pytest.raises(CommandRunnerError, match="rc=3"):
        r.run("exit 3")
    assert r.ready(timeout=5)
    src = tmp_path / "file.txt"
    src.write_text("payload")
    r.sync_up(str(src), str(tmp_path / "copied.txt"))
    assert (tmp_path / "copied.txt").read_text() == "payload"
    r.run_detached(f"sleep 0.2 && echo done > {tmp_path}/detached.txt")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if (tmp_path / "detached.txt").exists():
            break
        time.sleep(0.05)
    assert (tmp_path / "detached.txt").read_text().strip() == "done"


def test_ssh_runner_command_construction():
    r = SSHCommandRunner("10.0.0.5", user="ubuntu", ssh_key="/k.pem",
                         port=2222)
    base = r._ssh_base()
    assert "-i" in base and "/k.pem" in base
    assert "-p" in base and "2222" in base
    assert any("BatchMode=yes" in x for x in base)
    assert r._target() == "ubuntu@10.0.0.5"


def test_updater_lifecycle_runs_setup_then_start(tmp_path):
    log = tmp_path / "log.txt"
    updater = NodeUpdater(
        runner=SubprocessCommandRunner(cwd=str(tmp_path)),
        head_address="127.0.0.1:0",
        file_mounts={str(tmp_path / "src.txt"): str(tmp_path / "dst.txt")},
        setup_commands=[f"echo setup >> {log}"],
        start_command=f"echo start >> {log}",
    )
    (tmp_path / "src.txt").write_text("mounted")
    updater.update(ready_timeout=10)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if log.exists() and "start" in log.read_text():
            break
        time.sleep(0.05)
    assert log.read_text().splitlines() == ["setup", "start"]
    assert (tmp_path / "dst.txt").read_text() == "mounted"


def test_updater_joins_real_cluster(tmp_path):
    """E2E: head via `rt start --head`; a NodeUpdater (subprocess
    runner, as a local-provider host) brings up a worker that joins —
    the reference's updater->`ray start --address` flow."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli",
         "--num-cpus", "2", "start", "--head", "--port", "0",
         "--client-port", "0"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 120
        info = None
        while time.monotonic() < deadline:
            line = head.stdout.readline().strip()
            if line.startswith(b"{"):
                info = json.loads(line)
                break
        assert info, "head never printed its addresses"

        updater = NodeUpdater(
            runner=SubprocessCommandRunner(cwd=REPO),
            head_address=info["cluster_address"],
            setup_commands=["echo ready"],
            start_command=(
                f"{sys.executable} -m ray_tpu.scripts.cli --num-cpus 2 "
                f"start --address={info['cluster_address']} "
                "--resources '{\"updated\": 3}' --num-workers 1"),
            env={"PYTHONPATH": env["PYTHONPATH"]},
        )
        updater.update(ready_timeout=30)

        from ray_tpu.client import connect

        session = connect(info["client_address"])
        try:
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                res = session.cluster_info()["resources"]
                if res.get("updated", 0) >= 3:
                    break
                time.sleep(0.5)
            assert session.cluster_info()["resources"].get(
                "updated", 0) >= 3, "updated node never joined"
        finally:
            session.close()
    finally:
        head.terminate()
        head.wait(timeout=15)
        # The updater's daemon is detached; it dies with the head's
        # connection, but sweep any straggler to keep the box clean.
        subprocess.run(["pkill", "-f", "scripts.cli start --address"],
                       check=False)


def test_autoscaler_runs_updaters_for_launched_nodes():
    provider = FakeNodeProvider()
    config = AutoscalerConfig(node_types={
        "cpu": NodeType("cpu", {"CPU": 4}, min_workers=0, max_workers=3),
    })
    ran, fail_ids = [], []

    class DummyUpdater:
        def __init__(self, node_id, fail=False):
            self.node_id = node_id
            self.fail = fail

        def update(self):
            if self.fail:
                raise RuntimeError("bringup failed")
            ran.append(self.node_id)

    def factory(inst):
        fail = len(fail_ids) == 0
        if fail:
            fail_ids.append(inst.node_id)
        return DummyUpdater(inst.node_id, fail=fail)

    autoscaler = StandardAutoscaler(provider, config,
                                    updater_factory=factory)
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"CPU": 4}, {"CPU": 4}])
    autoscaler.update(metrics)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(ran) + len(autoscaler.updater_errors) >= 2:
            break
        time.sleep(0.05)
    assert len(ran) == 1
    assert list(autoscaler.updater_errors.values()) == [
        "RuntimeError('bringup failed')"]
    # The FAILED node is retried on the next tick (and succeeds);
    # successfully-updated nodes are NOT re-run.
    metrics.set_pending_demands([])
    autoscaler.update(metrics)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(ran) < 2:
        time.sleep(0.05)
    assert len(ran) == 2
    assert fail_ids[0] in ran  # the retried node came up
    assert not autoscaler.updater_errors  # cleared on success
    # Configured marker persisted via provider tags: a FRESH autoscaler
    # (simulated restart) does not re-run bring-up on configured hosts.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        tagged = [n for n in provider.non_terminated_nodes()
                  if n.tags.get("rt-configured")]
        if len(tagged) == 2:
            break
        time.sleep(0.05)
    assert len(tagged) == 2
    fresh = StandardAutoscaler(
        provider, config,
        updater_factory=lambda inst: DummyUpdater(inst.node_id))
    fresh.update(metrics)
    time.sleep(0.3)
    assert len(ran) == 2  # no re-run on the restarted autoscaler


def test_node_death_pushes_to_python_table(monkeypatch):
    """The native health checker's DEAD verdict reaches the Python node
    table via the push channel well before a poll interval elapses."""
    from ray_tpu.core.gcs_socket import build_native

    if not build_native():
        pytest.skip("native toolchain unavailable")
    from ray_tpu.core.gcs import NativeBackedControlStore, NodeInfo
    from ray_tpu.core.ids import NodeID

    store = NativeBackedControlStore()
    try:
        node_id = NodeID.from_random()
        store.register_node(NodeInfo(node_id=node_id,
                                     resources={"CPU": 1.0}))
        store.heartbeat(node_id)
        # Short detection period; the PUSH applies the verdict — the
        # poll fallback runs at 5x the period, so observing the death
        # well under that proves the streaming path.
        store.start_health_check(period_s=0.2, timeout_beats=2)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            with store._lock:
                node = store.nodes.get(node_id)
            if node is not None and not node.alive:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("node death never reached Python table")
        elapsed = 3.0 - (deadline - time.monotonic())
        assert elapsed < 1.0 * 5 * 0.2 + 1.0, elapsed
    finally:
        store.shutdown()
