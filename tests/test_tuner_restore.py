"""Experiment-level Tuner.restore: a killed sweep resumes with completed
trials intact.

The driver process running an ASHA sweep is SIGKILLed mid-experiment;
``Tuner.restore(path)`` then resumes it: the trial table, searcher
cursor, and scheduler rungs come back from ``experiment_state.pkl``, so
the total number of trials equals the original budget and no trial that
finished before the kill is retrained (reference: ``tune/tuner.py:159``
``Tuner.restore`` + ``tune/execution/trial_runner.py:682`` experiment
checkpointing).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

BUDGET = 6

_DRIVER = """
import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.schedulers import AsyncHyperBandScheduler
from ray_tpu.train.config import RunConfig
import trainable_mod

if __name__ == "__main__":
    rt.init(num_cpus=2)
    tuner = Tuner(
        trainable_mod.trainable,
        param_space={"x": tune.grid_search([1, 2, 3]),
                     "y": tune.grid_search([10, 20])},
        tune_config=TuneConfig(
            max_concurrent_trials=2,
            scheduler=AsyncHyperBandScheduler(
                metric="score", mode="max", max_t=40)),
        run_config=RunConfig(name="exp", storage_path=EXP_ROOT),
    )
    tuner.fit()
"""

_TRAINABLE = """
import os
import time

from ray_tpu import tune


def trainable(config):
    from ray_tpu.train.session import get_session

    trial_id = get_session().ctx.trial_id
    with open(os.path.join(EXP_ROOT, "starts.log"), "a") as f:
        f.write(trial_id + "\\n")
        f.flush()
    for i in range(40):
        tune.report({"score": config["x"] * config["y"] * (i + 1)})
        time.sleep(0.25)
"""


def test_tuner_restore_after_driver_kill(tmp_path):
    exp_root = str(tmp_path)
    exp_path = os.path.join(exp_root, "exp")
    # The trainable must be importable by name from BOTH the subprocess
    # driver and the restored in-process run (cloudpickle stores module
    # functions by reference only when importable; a file module makes
    # the restored state loadable here).
    (tmp_path / "trainable_mod.py").write_text(
        f"EXP_ROOT = {exp_root!r}\n" + _TRAINABLE)
    (tmp_path / "driver.py").write_text(
        f"EXP_ROOT = {exp_root!r}\n" + _DRIVER)

    env = dict(os.environ)
    env["PYTHONPATH"] = f"{tmp_path}:/root/repo:" + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, str(tmp_path / "driver.py")],
                            env=env, cwd=str(tmp_path))
    # load_state unpickles the trainable by module reference — make
    # trainable_mod importable in THIS process before polling.
    sys.path.insert(0, str(tmp_path))
    from ray_tpu.tune.tuner import TrialRunner, TrialStatus

    # Wait until at least one trial finished AND the sweep is not done,
    # then kill the driver hard (simulated preemption).
    deadline = time.monotonic() + 240
    pre_state = None
    try:
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail("driver finished before it could be killed "
                            f"(rc={proc.returncode})")
            try:
                state = TrialRunner.load_state(exp_path)
            except Exception:
                time.sleep(0.1)
                continue
            finished = [t for t in state["trials"]
                        if t.status in (TrialStatus.TERMINATED,
                                        TrialStatus.STOPPED)]
            in_flight = [t for t in state["trials"]
                         if t.status in (TrialStatus.RUNNING,
                                         TrialStatus.PENDING)]
            if finished and (in_flight
                             or len(state["trials"]) < BUDGET):
                pre_state = state
                break
            time.sleep(0.1)
        assert pre_state is not None, "no trial finished within deadline"
    finally:
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()

    finished_before = {t.trial_id for t in pre_state["trials"]
                       if t.status in (TrialStatus.TERMINATED,
                                       TrialStatus.STOPPED)}
    with open(os.path.join(exp_root, "starts.log")) as f:
        starts_before = f.read().splitlines()

    try:
        import ray_tpu as rt
        from ray_tpu.tune import Tuner

        # Explicit CPUs: auto_init sizes to the machine (1 core on the
        # bench box), which cannot host 2 concurrent trial actors.
        rt.init(num_cpus=4, ignore_reinit_error=True)
        assert Tuner.can_restore(exp_path)
        result = Tuner.restore(exp_path).fit()
    finally:
        sys.path.remove(str(tmp_path))
        try:
            rt.shutdown()
        except Exception:
            pass

    # Budget preserved: the grid is 3x2 = 6 trials, no more, no less.
    assert len(result.trials) == BUDGET, (
        f"expected {BUDGET} trials, got {len(result.trials)}")
    configs = sorted((t.config["x"], t.config["y"]) for t in result.trials)
    assert configs == sorted(
        (x, y) for x in (1, 2, 3) for y in (10, 20)), configs
    # Every trial ended (ASHA may stop some early; none left running).
    for t in result.trials:
        assert t.status in (TrialStatus.TERMINATED, TrialStatus.STOPPED,
                            TrialStatus.ERROR), t.status
    # No finished trial was retrained: its start count did not grow.
    with open(os.path.join(exp_root, "starts.log")) as f:
        starts_after = f.read().splitlines()
    for trial_id in finished_before:
        assert (starts_after.count(trial_id)
                == starts_before.count(trial_id)), (
            f"finished trial {trial_id} was retrained after restore")
