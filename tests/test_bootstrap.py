"""Bootstrap rendezvous tests: rank claiming, coordinator publish, barrier.

Reference coverage analog: collective group rendezvous tests
(util/collective) — N processes agree on ranks + a coordinator through
the store, without racing.
"""

import threading

import pytest

from ray_tpu.core.gcs_socket import ControlStoreProcess, build_native
from ray_tpu.parallel.bootstrap import Bootstrap, BootstrapError

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable")


@pytest.fixture()
def store():
    proc = ControlStoreProcess()
    clients = []

    def make_client():
        c = proc.client()
        clients.append(c)
        return c

    yield make_client
    for c in clients:
        c.close()
    proc.stop()


def test_concurrent_rank_claims_are_disjoint(store):
    world = 8
    results = {}
    errors = []
    barrier = threading.Barrier(world)

    def host(i):
        try:
            bs = Bootstrap(store(), world_size=world, session="s1")
            barrier.wait()  # maximal contention
            results[i] = bs.claim_rank()
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=host, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert sorted(results.values()) == list(range(world))


def test_extra_host_rejected(store):
    bs1 = Bootstrap(store(), world_size=1, session="s2")
    assert bs1.claim_rank() == 0
    bs2 = Bootstrap(store(), world_size=1, session="s2")
    with pytest.raises(BootstrapError):
        bs2.claim_rank()


def test_rank_reclaim_idempotent(store):
    client = store()
    bs = Bootstrap(client, world_size=2, session="s3")
    rank = bs.claim_rank()
    # Same Bootstrap (same token) re-claims its own slot after a restart.
    bs.rank = None
    assert bs.claim_rank() == rank


def test_coordinator_publish_and_poll(store):
    world = 3
    addresses = {}
    done = threading.Barrier(world)

    def host(i):
        bs = Bootstrap(store(), world_size=world, session="s4")
        bs.claim_rank()
        addresses[bs.rank] = bs.coordinator_address(port=12345,
                                                    timeout_s=10)
        done.wait()

    threads = [threading.Thread(target=host, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert len(set(addresses.values())) == 1  # everyone agrees
    assert addresses[0].endswith(":12345")


def test_barrier_blocks_until_all_arrive(store):
    world = 4
    order = []

    def host(i, delay):
        import time

        bs = Bootstrap(store(), world_size=world, session="s5")
        bs.claim_rank()
        time.sleep(delay)
        bs.barrier("sync", timeout_s=10)
        order.append(i)

    threads = [threading.Thread(target=host, args=(i, 0.2 * i))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert len(order) == world  # nobody timed out / deadlocked


def test_bootstrap_against_inprocess_store():
    """The same rendezvous works over the pure-Python control store."""
    from ray_tpu.core.gcs import GlobalControlStore

    gcs = GlobalControlStore()
    bs0 = Bootstrap(gcs, world_size=2, session="inproc")
    bs1 = Bootstrap(gcs, world_size=2, session="inproc")
    assert bs0.claim_rank() == 0
    assert bs1.claim_rank() == 1
    addr = bs0.coordinator_address(port=9999)
    assert bs1.coordinator_address(timeout_s=5) == addr
