"""Native shm arena store tests: C++ allocator + multiprocess access."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu._native import NativeStore, NativeStoreFull, available

pytestmark = pytest.mark.skipif(not available(),
                                reason="native store unavailable")


def _key(i: int) -> bytes:
    return i.to_bytes(4, "little") + b"\x00" * 16


def test_put_get_roundtrip():
    store = NativeStore.create("/rt_test_a", 4 * 1024 * 1024)
    try:
        data = os.urandom(1000)
        store.put(_key(1), data)
        view = store.get(_key(1))
        assert bytes(view) == data
        store.release(_key(1))
        assert store.contains(_key(1))
        assert not store.contains(_key(2))
    finally:
        store.close()


def test_delete_and_reuse_space():
    store = NativeStore.create("/rt_test_b", 1024 * 1024)
    try:
        big = b"x" * (600 * 1024)
        store.put(_key(1), big)
        with pytest.raises(NativeStoreFull):
            store.put(_key(2), big)
        assert store.delete(_key(1))
        store.put(_key(2), big)  # space reclaimed after free+coalesce
        assert store.contains(_key(2))
    finally:
        store.close()


def test_many_objects_alloc_free():
    store = NativeStore.create("/rt_test_c", 8 * 1024 * 1024)
    try:
        for i in range(500):
            store.put(_key(i), bytes([i % 256]) * (1000 + i))
        stats = store.stats()
        assert stats["num_objects"] == 500
        for i in range(0, 500, 2):
            store.delete(_key(i))
        assert store.stats()["num_objects"] == 250
        for i in range(500, 700):
            store.put(_key(i), b"y" * 2000)
        for i in range(1, 500, 2):
            assert bytes(store.get(_key(i))[:1]) == bytes([i % 256])
            store.release(_key(i))
    finally:
        store.close()


def _child_process(name, n):
    from ray_tpu._native import NativeStore

    store = NativeStore.attach(name)
    for i in range(n):
        store.put(i.to_bytes(4, "little") + b"\x01" + b"\x00" * 15,
                  b"from-child" + str(i).encode())
    store.close(unlink=False)


def test_multiprocess_shared_arena():
    store = NativeStore.create("/rt_test_d", 4 * 1024 * 1024)
    try:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_child_process, args=("/rt_test_d", 10))
        p.start()
        p.join(timeout=30)
        assert p.exitcode == 0
        for i in range(10):
            key = i.to_bytes(4, "little") + b"\x01" + b"\x00" * 15
            view = store.get(key)
            assert view is not None
            assert bytes(view) == b"from-child" + str(i).encode()
            store.release(key)
    finally:
        store.close()


def test_zero_copy_create_seal():
    store = NativeStore.create("/rt_test_e", 1024 * 1024)
    try:
        # put() path already covers copy; check stats accounting.
        arr = np.arange(1024, dtype=np.float32)
        store.put(_key(9), arr.tobytes())
        view = store.get(_key(9))
        out = np.frombuffer(view, dtype=np.float32)
        np.testing.assert_array_equal(out, arr)
        store.release(_key(9))
        assert store.stats()["used_bytes"] >= arr.nbytes
    finally:
        store.close()


def test_deferred_delete_under_pinned_reader():
    """delete-while-pinned defers the extent free until the last release;
    a put of the same key while pending raises instead of silently
    dropping data (plasma-style safety for zero-copy readers)."""
    from ray_tpu._native import NativeStorePendingDelete

    store = NativeStore.create("/rt_test_pd", 1024 * 1024)
    try:
        store.put(_key(40), b"payload-a")
        view = store.get(_key(40))  # pin
        used_before = store.stats()["used_bytes"]
        assert store.delete(_key(40))  # deferred, key gone immediately
        assert not store.contains(_key(40))
        # the pinned zero-copy view stays valid and bytes stay allocated
        assert bytes(view[:9]) == b"payload-a"
        assert store.stats()["used_bytes"] == used_before
        try:
            store.put(_key(40), b"payload-b")
            raise AssertionError("put over pending-delete must raise")
        except NativeStorePendingDelete:
            pass
        store.release(_key(40))  # last reader -> extent freed
        assert store.stats()["used_bytes"] < used_before
        store.put(_key(40), b"payload-b")
        view2 = store.get(_key(40))
        assert bytes(view2[:9]) == b"payload-b"
        store.release(_key(40))
    finally:
        store.close()


def test_sliver_absorb_accounting():
    """Alloc/free churn with absorbed slivers must return used_bytes to
    baseline (regression: absorbed sliver bytes were leaked)."""
    store = NativeStore.create("/rt_test_sl", 1024 * 1024)
    try:
        baseline = store.stats()["used_bytes"]
        for round_ in range(50):
            keys = [(1000 + round_ * 10 + i) for i in range(8)]
            for i, k in enumerate(keys):
                store.put(_key(k), bytes(37 + 61 * i))
            for k in keys:
                assert store.delete(_key(k))
        assert store.stats()["used_bytes"] == baseline
    finally:
        store.close()


def test_create_object_write_seal_pinned_get():
    """Two-phase zero-copy write (create_object/seal; reference: plasma
    Create/Seal) + get_pinned lifetime: the pin releases when the last
    derived view is collected, and delete-while-pinned defers the free."""
    import gc

    from ray_tpu._native import NativeStoreExists, NativeStoreFull

    store = NativeStore.create("/rt_test_zc2", 1024 * 1024)
    try:
        arr = np.arange(4096, dtype=np.float64)
        view = store.create_object(_key(50), arr.nbytes)
        assert not view.readonly
        view[:] = arr.tobytes()
        view.release()
        store.seal(_key(50))
        try:
            store.create_object(_key(50), 8)
            raise AssertionError("duplicate create must raise")
        except NativeStoreExists:
            pass
        g = store.get_pinned(_key(50))
        assert g.readonly
        out = np.frombuffer(g, dtype=np.float64)
        np.testing.assert_array_equal(out, arr)
        used = store.stats()["used_bytes"]
        store.delete(_key(50))  # deferred: `out` still pins the extent
        assert store.stats()["used_bytes"] == used
        np.testing.assert_array_equal(out, arr)
        del g, out
        gc.collect()
        assert store.stats()["used_bytes"] < used  # pin released on GC
        # abort frees an unsealed reservation
        v2 = store.create_object(_key(51), 512)
        v2.release()
        store.abort(_key(51))
        assert store.get(_key(51)) is None
        # oversized create reports full
        try:
            store.create_object(_key(52), 8 * 1024 * 1024)
            raise AssertionError("oversized create must raise")
        except NativeStoreFull:
            pass
    finally:
        store.close()
