"""Tracing spans + TPE searcher tests.

Reference coverage analog: tracing_helper tests (spans around
submit/execute, context propagation) and hyperopt searcher tests
(model-based search beats random on a smooth objective).
"""

import random

import pytest


# -- tracing -----------------------------------------------------------------

def test_span_nesting_and_records():
    from ray_tpu.observability import tracing

    tracer = tracing.get_tracer()
    tracer.clear()
    tracing.enable()
    try:
        with tracing.span("outer", kind="test") as outer:
            with tracing.span("inner") as inner:
                assert inner.parent_id == outer.span_id
                assert inner.trace_id == outer.trace_id
        spans = tracer.spans()
        names = [s.name for s in spans]
        assert names == ["inner", "outer"]  # completion order
        assert all(s.duration_ms is not None for s in spans)
        events = tracer.chrome_trace_events()
        assert len(events) == 2 and events[0]["ph"] == "X"
    finally:
        tracing.disable()
        tracer.clear()


def test_disabled_tracer_is_noop():
    from ray_tpu.observability import tracing

    tracing.disable()
    with tracing.span("ghost") as s:
        assert s is None
    assert tracing.get_tracer().spans("ghost") == []


def test_trace_span_decorator():
    from ray_tpu.observability import tracing

    tracing.enable()
    try:
        @tracing.trace_span("decorated")
        def fn(x):
            return x + 1

        assert fn(1) == 2
        assert tracing.get_tracer().spans("decorated")
    finally:
        tracing.disable()
        tracing.get_tracer().clear()


def test_submission_spans_and_remote_context(monkeypatch):
    """End-to-end: driver records task.submit spans; workers adopt the
    submitted trace context so their execution joins the trace."""
    monkeypatch.setenv("RT_TRACING_ENABLED", "1")
    from ray_tpu.core.config import Config

    Config.reset()
    import ray_tpu as rt
    from ray_tpu.observability import tracing

    if rt.is_initialized():
        rt.shutdown()  # don't collide with module-shared runtimes
    rt.init(num_cpus=2)
    try:
        with tracing.span("driver-root"):
            @rt.remote
            def traced_task():
                from ray_tpu.observability import tracing as wtr

                # The worker-side execute span carries the driver's trace.
                spans = wtr.get_tracer().spans("task.execute")
                cur = wtr.current_span()
                return (cur is not None, cur.trace_id if cur else None)

            has_span, trace_id = rt.get(traced_task.remote())
        assert has_span
        root = tracing.get_tracer().spans("driver-root")[0]
        assert trace_id == root.trace_id, "worker span must join the trace"
        submits = tracing.get_tracer().spans("task.submit")
        assert submits and submits[0].trace_id == root.trace_id
    finally:
        rt.shutdown()
        tracing.disable()
        tracing.get_tracer().clear()
        Config.reset()


# -- TPE searcher ------------------------------------------------------------

def _quadratic(cfg):
    return (cfg["x"] - 0.7) ** 2 + (cfg["y"] - 0.3) ** 2


def test_tpe_beats_random_on_quadratic():
    from ray_tpu.tune.search import TPESearcher, Uniform

    space = {"x": Uniform(0, 1), "y": Uniform(0, 1)}

    def run(searcher_factory, n=60, seed=0):
        best = float("inf")
        searcher = searcher_factory()
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            score = _quadratic(cfg)
            searcher.on_trial_complete(f"t{i}", {"loss": score})
            best = min(best, score)
        return best

    tpe_best = run(lambda: TPESearcher(space, metric="loss", mode="min",
                                       n_startup_trials=10, seed=1))

    rng = random.Random(1)
    rand_best = min(
        _quadratic({"x": rng.uniform(0, 1), "y": rng.uniform(0, 1)})
        for _ in range(60))
    # TPE should at least match pure random at equal budget (usually far
    # better); a loose factor keeps the test seed-robust.
    assert tpe_best <= rand_best * 1.5, (tpe_best, rand_best)
    assert tpe_best < 0.02


def test_tpe_handles_all_domain_types():
    from ray_tpu.tune.search import (
        Choice,
        LogUniform,
        RandInt,
        TPESearcher,
        Uniform,
    )

    space = {
        "lr": LogUniform(1e-5, 1e-1),
        "width": RandInt(8, 256),
        "act": Choice(["relu", "tanh", "gelu"]),
        "drop": Uniform(0.0, 0.5),
        "fixed": 42,
    }
    searcher = TPESearcher(space, metric="score", mode="max",
                           n_startup_trials=5, seed=0)
    for i in range(20):
        cfg = searcher.suggest(f"t{i}")
        assert 1e-5 <= cfg["lr"] <= 1e-1
        assert 8 <= cfg["width"] < 256
        assert cfg["act"] in ("relu", "tanh", "gelu")
        assert cfg["fixed"] == 42
        searcher.on_trial_complete(f"t{i}", {"score": cfg["drop"]})


def test_tpe_max_trials_exhausts():
    from ray_tpu.tune.search import TPESearcher, Uniform

    searcher = TPESearcher({"x": Uniform(0, 1)}, metric="loss",
                           max_trials=3, seed=0)
    assert all(searcher.suggest(f"t{i}") is not None for i in range(3))
    assert searcher.suggest("t3") is None


def test_tpe_in_tuner(rt_shared):
    from ray_tpu.tune import Tuner
    from ray_tpu.tune.search import TPESearcher, Uniform

    def objective(config):
        from ray_tpu.tune import report

        report({"loss": (config["x"] - 0.5) ** 2})

    from ray_tpu.tune import TuneConfig

    searcher = TPESearcher({"x": Uniform(0, 1)}, metric="loss", mode="min",
                           n_startup_trials=4, max_trials=10, seed=0)
    tuner = Tuner(objective,
                  tune_config=TuneConfig(search_alg=searcher,
                                         max_concurrent_trials=2))
    grid = tuner.fit()
    best = grid.get_best_result("loss", mode="min")
    assert best.last_result["loss"] < 0.1


def test_actor_execution_traced(monkeypatch):
    monkeypatch.setenv("RT_TRACING_ENABLED", "1")
    from ray_tpu.core.config import Config

    Config.reset()
    import ray_tpu as rt
    from ray_tpu.observability import tracing

    if rt.is_initialized():
        rt.shutdown()  # don't collide with module-shared runtimes
    rt.init(num_cpus=2)
    try:
        @rt.remote
        class Probe:
            def look(self):
                from ray_tpu.observability import tracing as wtr

                cur = wtr.current_span()
                return cur.name if cur else None

        p = Probe.remote()
        name = rt.get(p.look.remote())
        assert name and name.startswith("task.execute")
    finally:
        rt.shutdown()
        tracing.disable()
        tracing.get_tracer().clear()
        Config.reset()


def test_tpe_zero_startup_does_not_crash():
    from ray_tpu.tune.search import TPESearcher, Uniform

    s = TPESearcher({"x": Uniform(0, 1)}, metric="loss",
                    n_startup_trials=0, seed=0)
    cfg = s.suggest("t0")  # empty history must fall back to random
    assert 0 <= cfg["x"] <= 1


def test_tpe_rejects_grid_search():
    import pytest as _pytest

    from ray_tpu.tune.search import GridSearch, TPESearcher

    with _pytest.raises(ValueError):
        TPESearcher({"bs": GridSearch([32, 64])}, metric="loss")
