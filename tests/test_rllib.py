"""RLlib tests: sampling, GAE, PPO learning on CartPole.

Mirrors reference coverage: rllib/utils/test_utils.py
check_compute_single_action / learning tests with reward thresholds.
"""

import numpy as np
import pytest


def test_fast_cartpole_matches_gym_api():
    from ray_tpu.rllib import FastCartPole

    env = FastCartPole(4, seed=0)
    obs = env.vector_reset()
    assert obs.shape == (4, 4)
    for _ in range(10):
        obs, rew, done, _ = env.vector_step(np.array([1, 0, 1, 0]))
    assert obs.shape == (4, 4)
    assert rew.shape == (4,)


def test_gae_computation():
    from ray_tpu.rllib.sample_batch import (
        DONES, REWARDS, VF_PREDS, ADVANTAGES, VALUE_TARGETS,
        SampleBatch, compute_gae,
    )

    batch = SampleBatch({
        REWARDS: np.ones((3, 1), np.float32),
        DONES: np.zeros((3, 1), bool),
        VF_PREDS: np.zeros((3, 1), np.float32),
    })
    out = compute_gae(batch, np.zeros(1, np.float32), gamma=1.0, lam=1.0)
    # With gamma=lam=1, v=0: advantage[t] = sum of future rewards.
    np.testing.assert_allclose(out[ADVANTAGES][:, 0], [3, 2, 1])
    np.testing.assert_allclose(out[VALUE_TARGETS][:, 0], [3, 2, 1])


def test_rollout_worker_sample_shapes(rt_shared):
    from ray_tpu.rllib import RolloutWorker

    w = RolloutWorker("FastCartPole", num_envs=4, seed=0)
    batch = w.sample(16)
    assert batch["obs"].shape == (16, 4, 4)
    assert batch["actions"].shape == (16, 4)
    assert batch["last_values"].shape == (4,)


def test_ppo_single_iteration(rt_shared):
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=64)
            .training(sgd_minibatch_size=64, num_sgd_iter=2)
            .build())
    result = algo.train()
    assert result["training_iteration"] == 1
    assert result["timesteps_this_iter"] == 256
    assert np.isfinite(result["total_loss"])
    algo.stop()


def test_ppo_remote_workers(rt_shared):
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=32)
            .training(sgd_minibatch_size=32, num_sgd_iter=2)
            .build())
    result = algo.train()
    assert result["timesteps_this_iter"] == 2 * 2 * 32
    algo.stop()


def test_ppo_save_restore(rt_shared, tmp_path):
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("FastCartPole")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
            .training(sgd_minibatch_size=32, num_sgd_iter=1)
            .build())
    algo.train()
    path = algo.save(str(tmp_path))
    w0 = algo.workers.local_worker.get_weights()
    algo.stop()

    algo2 = (PPOConfig()
             .environment("FastCartPole")
             .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
             .training(sgd_minibatch_size=32, num_sgd_iter=1)
             .build())
    algo2.restore(path)
    w1 = algo2.workers.local_worker.get_weights()
    np.testing.assert_allclose(w0["pi_w"], w1["pi_w"])
    assert algo2.iteration == 1
    algo2.stop()


@pytest.mark.slow
def test_ppo_learns_cartpole(rt_shared):
    """Learning test: reward must clearly improve in bounded iterations
    (reference: rllib learning tests assert reward thresholds)."""
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=128)
            .training(lr=1e-3, sgd_minibatch_size=512, num_sgd_iter=4)
            .debugging(seed=0)
            .build())
    best = 0.0
    for i in range(15):
        result = algo.train()
        r = result.get("episode_reward_mean")
        if r is not None:
            best = max(best, r)
        if best >= 120:
            break
    algo.stop()
    assert best >= 120, f"PPO failed to learn CartPole (best={best})"


def test_conv_policy_shapes():
    """Nature-CNN policy over Atari-shaped frames (reference:
    rllib/models/catalog.py conv stacks for image obs)."""
    import numpy as np
    from ray_tpu.rllib.policy import JaxPolicy

    pol = JaxPolicy((84, 84, 4), 6, network="auto")
    assert pol.net.kind == "conv"
    obs = np.random.randint(0, 255, (3, 84, 84, 4), dtype=np.uint8)
    actions, logp, values = pol.compute_actions(obs)
    assert actions.shape == (3,) and values.shape == (3,)
    assert (actions >= 0).all() and (actions < 6).all()


def test_ppo_conv_actor_path_smoke():
    """Actor-based PPO trains one iteration on the Atari-shaped env."""
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment("AtariSim")
              .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                        rollout_fragment_length=8)
              .training(train_batch_size=16, sgd_minibatch_size=8,
                        num_sgd_iter=1))
    algo = config.build()
    try:
        result = algo.train()
        assert result["timesteps_this_iter"] >= 16
    finally:
        algo.stop()


def test_ondevice_ppo_iteration():
    """Fused rollout+GAE+SGD program runs and improves CartPole quickly
    (the TPU-native PPO fast path, ray_tpu/rllib/ondevice.py)."""
    from ray_tpu.rllib.ondevice import OnDevicePPO, jax_cartpole

    algo = OnDevicePPO(jax_cartpole(32), rollout_length=32, minibatches=4,
                       num_sgd_iter=2, seed=3)
    m = algo.train_iteration()
    assert m["timesteps_this_iter"] == 32 * 32
    assert np.isfinite(m["total_loss"])


@pytest.mark.slow
def test_ondevice_ppo_learns_cartpole():
    """Bounded-time learning criterion on the fused path (reference:
    rllib learning tests assert reward thresholds in bounded time)."""
    from ray_tpu.rllib.ondevice import OnDevicePPO, jax_cartpole

    algo = OnDevicePPO(jax_cartpole(64), rollout_length=128,
                       minibatches=8, num_sgd_iter=4, seed=0)
    episode_len = 0.0
    for i in range(120):
        m = algo.train_iteration()
        episode_len = m["mean_episode_len"]
        if episode_len >= 128.0:  # episodes now outlast the rollout
            break
    assert episode_len >= 128.0, f"did not learn: ep_len~{episode_len:.0f}"
