"""Train library tests: WorkerGroup, DataParallelTrainer, session,
checkpoints. Mirrors reference ``python/ray/train/tests/test_backend.py`` /
``test_data_parallel_trainer.py`` coverage."""

import os

import pytest


def test_worker_group_execute(rt_shared):
    from ray_tpu.train import WorkerGroup

    wg = WorkerGroup(2, resources_per_worker={"CPU": 1})
    try:
        ranks = wg.execute(lambda: __import__("os").getpid())
        assert len(ranks) == 2
        assert ranks[0] != ranks[1]  # distinct processes
    finally:
        wg.shutdown()


def test_worker_group_session_ranks(rt_shared):
    from ray_tpu.train import WorkerGroup

    wg = WorkerGroup(2, resources_per_worker={"CPU": 1})
    try:
        def get_rank():
            from ray_tpu.train import session

            return (session.get_world_rank(), session.get_world_size())

        out = wg.execute(get_rank)
        assert sorted(out) == [(0, 2), (1, 2)]
    finally:
        wg.shutdown()


def test_data_parallel_trainer_basic(rt_shared):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def train_fn(config):
        from ray_tpu.train import session

        for step in range(config["steps"]):
            session.report({"step": step, "loss": 1.0 / (step + 1)})

    trainer = DataParallelTrainer(
        train_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert result.metrics["loss"] == pytest.approx(1 / 3)
    # 2 workers x 3 reports
    assert len(result.metrics_history) == 6


def test_trainer_checkpointing(rt_shared, tmp_path):
    from ray_tpu.train import (
        Checkpoint,
        DataParallelTrainer,
        RunConfig,
        ScalingConfig,
    )

    def train_fn(config):
        from ray_tpu.train import session

        for step in range(3):
            ckpt = None
            if session.get_world_rank() == 0:
                ckpt = Checkpoint.from_dict({"model_step": step})
            session.report({"step": step}, checkpoint=ckpt)

    trainer = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt-test", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["model_step"] == 2


def test_trainer_error_surfaces(rt_shared):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def train_fn(config):
        raise ValueError("train blew up")

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1)
    )
    result = trainer.fit()
    assert not result.ok
    assert "train blew up" in result.error


def test_checkpoint_roundtrip(tmp_path):
    import numpy as np

    from ray_tpu.train import Checkpoint

    ckpt = Checkpoint.from_dict(
        {"x": 1, "__arrays__": {"w": np.ones((4, 4), np.float32)}}
    )
    path = ckpt.to_directory(str(tmp_path / "c1"))
    restored = Checkpoint.from_directory(path).to_dict()
    assert restored["x"] == 1
    np.testing.assert_array_equal(restored["__arrays__"]["w"], np.ones((4, 4)))


def test_checkpoint_manager_retention(tmp_path):
    from ray_tpu.train import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path), num_to_keep=2)
    for i in range(5):
        mgr.save(Checkpoint.from_dict({"i": i}), i)
    kept = sorted(os.listdir(str(tmp_path)))
    assert len(kept) == 2
    assert mgr.latest().to_dict()["i"] == 4


def test_batch_predictor_end_to_end(rt_init):
    """checkpoint -> BatchPredictor -> Dataset of predictions
    (reference: train/batch_predictor.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.data import from_items
    from ray_tpu.train import BatchPredictor, Checkpoint, JaxPredictor

    # A "trained" linear model checkpoint.
    params = {"w": jnp.asarray([[2.0], [1.0]]), "b": jnp.asarray([0.5])}
    ckpt = Checkpoint.from_dict({"params": jax.tree.map(np.asarray, params)})

    def apply_fn(p, batch):
        return {"pred": batch["x"] @ jnp.asarray(p["w"])
                + jnp.asarray(p["b"])}

    rows = [{"x": np.asarray([float(i), float(2 * i)], np.float32)}
            for i in range(12)]
    ds = from_items(rows, parallelism=3)
    predictor = BatchPredictor.from_checkpoint(
        ckpt, JaxPredictor, apply_fn=apply_fn)
    out = predictor.predict(ds, max_scoring_workers=2)
    preds = sorted(float(r["pred"][0]) for r in out.iter_rows())
    want = sorted(2.0 * i + 1.0 * 2 * i + 0.5 for i in range(12))
    np.testing.assert_allclose(preds, want, rtol=1e-5)


def test_async_checkpoint_save(tmp_path):
    """save_async snapshots device state immediately and lands on disk in
    the background (SURVEY §7.2 stage 6 orbax-style async save)."""
    import numpy as np

    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), num_to_keep=2)
    arr = np.arange(8, dtype=np.float32)
    fut = mgr.save_async(Checkpoint.from_dict({"params": arr, "step": 1}),
                         step=1, metrics={"loss": 1.0})
    # MUTATE the source after save_async returns: the snapshot taken at
    # call time must win (consistency with the training step).
    arr += 100.0
    path = fut.result(timeout=30)
    mgr.wait_async()
    restored = Checkpoint.from_directory(path).to_dict()
    np.testing.assert_array_equal(restored["params"],
                                  np.arange(8, dtype=np.float32))
    assert restored["step"] == 1
    assert mgr.latest() is not None
