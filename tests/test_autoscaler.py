"""Autoscaler tests: bin-packing logic + e2e with the local provider.

Mirrors reference coverage: ``tests/test_resource_demand_scheduler.py``
(pure bin-packing), ``tests/test_autoscaler.py`` (mocked provider),
``tests/test_autoscaler_fake_multinode.py`` (e2e).
"""

import time

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    LoadMetrics,
    NodeType,
    ResourceDemandScheduler,
    StandardAutoscaler,
)


def _config():
    return AutoscalerConfig(node_types={
        "cpu4": NodeType("cpu4", {"CPU": 4}, max_workers=5),
        "tpu8": NodeType("tpu8", {"CPU": 8, "TPU": 8}, max_workers=2,
                         topology={"tpu_slice": "v5e-8", "chips": 8}),
    }, max_workers=6, idle_timeout_s=0.2)


def test_bin_packing_launches_for_demand():
    sched = ResourceDemandScheduler(_config())
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"CPU": 2}] * 4)  # 8 CPUs wanted
    out = sched.get_nodes_to_launch(metrics, {})
    assert out == {"cpu4": 2}  # two 4-CPU nodes pack 4x2-CPU demands


def test_bin_packing_uses_existing_capacity():
    sched = ResourceDemandScheduler(_config())
    metrics = LoadMetrics()
    metrics.update_node("n1", {"CPU": 4}, {"CPU": 4})  # 4 CPUs free
    metrics.set_pending_demands([{"CPU": 2}, {"CPU": 2}])
    out = sched.get_nodes_to_launch(metrics, {"cpu4": 1})
    assert out == {}  # fits in the free node


def test_tpu_demand_selects_tpu_type():
    sched = ResourceDemandScheduler(_config())
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"TPU": 8}])
    out = sched.get_nodes_to_launch(metrics, {})
    assert out == {"tpu8": 1}


def test_max_workers_cap():
    sched = ResourceDemandScheduler(_config())
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"CPU": 4}] * 20)
    out = sched.get_nodes_to_launch(metrics, {})
    assert sum(out.values()) <= 6


def test_min_workers_floor():
    cfg = AutoscalerConfig(node_types={
        "base": NodeType("base", {"CPU": 2}, min_workers=2),
    })
    sched = ResourceDemandScheduler(cfg)
    out = sched.get_nodes_to_launch(LoadMetrics(), {})
    assert out == {"base": 2}


def test_standard_autoscaler_scales_up_and_down():
    provider = FakeNodeProvider()
    autoscaler = StandardAutoscaler(provider, _config())
    metrics = LoadMetrics()
    metrics.set_pending_demands([{"CPU": 3}])
    autoscaler.update(metrics)
    assert len(provider.non_terminated_nodes()) == 1
    # Demand satisfied; node reported idle -> terminated after timeout.
    nid = provider.non_terminated_nodes()[0].node_id
    metrics.set_pending_demands([])
    metrics.update_node(nid, {"CPU": 4}, {"CPU": 4})
    metrics.last_active[nid] = time.monotonic() - 10  # long idle
    autoscaler.update(metrics)
    assert len(provider.non_terminated_nodes()) == 0


def test_autoscaler_e2e_with_cluster(rt_cluster):
    """Infeasible task -> autoscaler launches a real node -> task runs."""
    import ray_tpu as rt
    from ray_tpu.autoscaler.providers import LocalNodeProvider

    cluster = rt_cluster
    cfg = AutoscalerConfig(node_types={
        "accel": NodeType("accel", {"CPU": 2, "accel": 1}, max_workers=2),
    }, idle_timeout_s=999)
    provider = LocalNodeProvider(cluster, cfg.node_types)
    autoscaler = StandardAutoscaler(provider, cfg)

    @rt.remote(resources={"accel": 1})
    def needs_accel():
        return "scaled!"

    ref = needs_accel.remote()
    ready, _ = rt.wait([ref], timeout=0.5)
    assert not ready  # infeasible on the head node
    metrics = LoadMetrics.from_runtime(cluster.runtime)
    assert metrics.pending_demands
    launched = autoscaler.update(metrics)
    assert launched == {"accel": 1}
    assert rt.get(ref, timeout=60) == "scaled!"
