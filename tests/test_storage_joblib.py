"""Storage API + joblib backend tests.

Reference coverage analog: python/ray/tests/test_storage.py and
ray.util.joblib tests.
"""

import pytest


def test_storage_client_roundtrip(tmp_path):
    from ray_tpu.core import storage

    storage._init_storage(str(tmp_path))
    try:
        client = storage.get_client("ns1")
        assert client.get("missing") is None
        client.put("a/b.bin", b"payload")
        assert client.get("a/b.bin") == b"payload"
        assert client.exists("a/b.bin")
        client.put("a/c.bin", b"x")
        assert client.list("a") == ["a/b.bin", "a/c.bin"]
        # scoped prefixes are disjoint
        other = storage.get_client("ns2")
        assert other.get("a/b.bin") is None
        assert client.delete("a/b.bin")
        assert not client.delete("a/b.bin")
        assert client.delete_dir("a")
    finally:
        storage._init_storage(None)


def test_storage_key_escape_rejected(tmp_path):
    from ray_tpu.core import storage

    storage._init_storage(str(tmp_path))
    try:
        client = storage.get_client("ns")
        with pytest.raises(ValueError):
            client.put("../escape", b"nope")
        # Sibling whose name shares the prefix ("ns" vs "ns2"): a bare
        # startswith check wrongly admits this.
        ns2 = storage.get_client("ns2")
        ns2.put("secret", b"mine")
        with pytest.raises(ValueError):
            client.get("../ns2/secret")
    finally:
        storage._init_storage(None)


def test_storage_visible_inside_workers(tmp_path):
    import ray_tpu as rt
    from ray_tpu.core import storage

    rt.init(num_cpus=2, storage=str(tmp_path))
    try:
        @rt.remote
        def write_from_worker():
            from ray_tpu.core import storage as s

            s.get_client("wf").put("from-worker", b"ok")
            return True

        assert rt.get(write_from_worker.remote())
        assert storage.get_client("wf").get("from-worker") == b"ok"
    finally:
        rt.shutdown()
        storage._init_storage(None)


def test_storage_unconfigured_raises():
    from ray_tpu.core import storage

    assert storage.get_storage_uri() is None
    with pytest.raises(RuntimeError):
        storage.get_client()


def test_init_accepts_storage(tmp_path, monkeypatch):
    import ray_tpu as rt
    from ray_tpu.core import storage

    rt.init(num_cpus=2, storage=str(tmp_path))
    try:
        client = storage.get_client("workflow")
        client.put("k", b"v")
        assert client.get("k") == b"v"
    finally:
        rt.shutdown()
        storage._init_storage(None)


def test_joblib_backend(rt_shared):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        results = joblib.Parallel(n_jobs=4)(
            joblib.delayed(lambda x: x * x)(i) for i in range(10))
    assert results == [i * i for i in range(10)]
