"""Ape-X distributed prioritized replay (VERDICT r4 item 7): sharded
replay actors + priority-shipping rollout workers + async learner, and
it must actually learn CartPole."""

import numpy as np
import pytest

from ray_tpu.rllib.apex import ApexConfig


def test_apex_smoke_distributed_plumbing():
    """Two rollout workers, two replay shards: adds, prioritized
    samples, and priority updates all flow as actor RPCs."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=4)
    config = (
        ApexConfig()
        .environment("FastCartPole")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                  rollout_fragment_length=16)
        .training(train_batch_size=32, learning_starts=0,
                  num_updates_per_iter=2, num_replay_shards=2,
                  weight_sync_period=4)
        .debugging(seed=0)
    )
    config.policy_hidden = (32, 32)
    algo = config.build()
    try:
        updates = 0
        for _ in range(8):
            r = algo.train()
            updates = r["num_learner_updates"]
        assert updates > 0
        stats = r["replay_shards"]
        assert len(stats) == 2
        assert all(s["adds"] > 0 for s in stats), stats
        assert sum(s["samples"] for s in stats) > 0, stats
        assert r["replay_buffer_size"] > 0
        assert np.isfinite(r["loss"])
    finally:
        algo.stop()
        rt.shutdown()


@pytest.mark.slow
def test_apex_learns_cartpole():
    """Learning proof on the sharded-replay path (reference release
    criterion; wall-clock superiority over 1-buffer DQN needs real
    parallel cores — this box has one, so the assertion is learning,
    with the distributed tier active)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=4)
    config = (
        ApexConfig()
        .environment("FastCartPole")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                  rollout_fragment_length=16)
        .training(lr=1e-3, train_batch_size=128, learning_starts=500,
                  num_updates_per_iter=8, num_replay_shards=2,
                  target_network_update_freq=100, weight_sync_period=8)
        .debugging(seed=0)
    )
    config.policy_hidden = (64, 64)
    algo = config.build()
    best = -np.inf
    try:
        for _ in range(250):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if best >= 130.0:
                break
    finally:
        algo.stop()
        rt.shutdown()
    assert best >= 130.0, f"Ape-X did not learn CartPole (best={best:.0f})"
