"""Robust shm mutex: a worker killed inside the arena's critical section
must not wedge the node.

The arena is guarded by a PTHREAD_MUTEX_ROBUST process-shared mutex; a
client that dies holding it hands EOWNERDEAD to the next locker, which
repairs the allocator (rebuilds the free list from the object table,
tombstones torn slots) before marking the mutex consistent. Reference
concern: plasma's server-mediated design never exposes clients to each
other's locks (``plasma/store.h:55``); the direct-mapped arena must earn
that same safety.
"""

import ctypes
import multiprocessing
import os

import pytest

from ray_tpu._native import NativeStore, _load_lib

_MP = multiprocessing.get_context("spawn")


def _die_holding_lock(name: str) -> None:
    from ray_tpu._native import NativeStore, _load_lib

    store = NativeStore.attach(name)
    lib = _load_lib()
    lib.rt_store_test_lock_hold.argtypes = [ctypes.c_void_p]
    lib.rt_store_test_lock_hold.restype = ctypes.c_int32
    assert lib.rt_store_test_lock_hold(store._handle) == 0
    os._exit(0)  # exit while holding the mutex


def _die_mid_alloc(name: str) -> None:
    from ray_tpu._native import NativeStore, _load_lib

    store = NativeStore.attach(name)
    lib = _load_lib()
    lib.rt_store_test_die_mid_alloc.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_test_die_mid_alloc.restype = ctypes.c_int32
    assert lib.rt_store_test_die_mid_alloc(
        store._handle, b"tornslot" + bytes(12)) == 0
    os._exit(0)


def _put_loop_victim(name: str, barrier) -> None:
    """Hammer puts until killed (the chaos scenario from VERDICT r3)."""
    from ray_tpu._native import NativeStore

    store = NativeStore.attach(name)
    barrier.wait()
    i = 0
    while True:
        key = b"victim" + i.to_bytes(14, "little")
        try:
            store.put(key, b"v" * 4096)
            store.delete(key)
        except Exception:
            pass
        i += 1


@pytest.fixture
def arena():
    name = f"/rt_test_robust_{os.getpid()}"
    store = NativeStore.create(name, 16 * 1024 * 1024)
    yield name, store
    store.close(unlink=True)


def test_dead_lock_holder_does_not_wedge(arena):
    name, store = arena
    store.put(b"live-object" + bytes(9), b"x" * 1000)

    p = _MP.Process(target=_die_holding_lock, args=(name,))
    p.start()
    p.join(30)
    assert p.exitcode == 0

    # Next operation takes EOWNERDEAD, repairs, and proceeds.
    store.put(b"after-death" + bytes(9), b"y" * 2000)
    got = store.get(b"live-object" + bytes(9))
    assert got is not None and bytes(got) == b"x" * 1000
    store.release(b"live-object" + bytes(9))
    got = store.get(b"after-death" + bytes(9))
    assert got is not None and bytes(got) == b"y" * 2000
    store.release(b"after-death" + bytes(9))


def test_death_mid_alloc_repairs_allocator(arena):
    name, store = arena
    store.put(b"survivor-obj" + bytes(8), b"s" * 5000)
    used_before = store.stats()["used_bytes"]

    p = _MP.Process(target=_die_mid_alloc, args=(name,))
    p.start()
    p.join(30)
    assert p.exitcode == 0

    # Repair must tombstone the torn slot, rebuild the free list (the
    # test hook dangled free_head), and keep the survivor readable.
    stats = store.stats()
    assert stats["num_objects"] == 1
    assert stats["used_bytes"] == used_before
    got = store.get(b"survivor-obj" + bytes(8))
    assert got is not None and bytes(got) == b"s" * 5000
    store.release(b"survivor-obj" + bytes(8))
    # Allocator is healthy: a put close to remaining capacity succeeds.
    store.put(b"big-after-fix" + bytes(7), b"z" * (8 * 1024 * 1024))
    store.delete(b"big-after-fix" + bytes(7))


def test_sigkill_during_put_loop(arena):
    """End-to-end chaos: SIGKILL a worker mid-put-loop; the node's other
    clients keep making progress."""
    name, store = arena
    barrier = _MP.Barrier(2)
    p = _MP.Process(target=_put_loop_victim, args=(name, barrier))
    p.start()
    barrier.wait()
    import time

    for round_i in range(3):
        time.sleep(0.05)
        if round_i == 1:
            p.kill()  # SIGKILL mid-loop (possibly mid-critical-section)
            p.join(30)
        key = f"progress-{round_i}".encode().ljust(20, b"\0")
        store.put(key, b"p" * 10000)
        got = store.get(key)
        assert got is not None and bytes(got) == b"p" * 10000
        store.release(key)
    assert not p.is_alive()
