"""Runtime-env pip isolation: offline per-env-hash materialization from
a local wheel dir (reference: ``_private/runtime_env/pip.py`` venv per
env hash; network installs are forbidden in this environment, so the
build is --no-index over local wheels)."""

import base64
import hashlib
import os
import zipfile

import pytest


def _make_wheel(path: str, pkg: str, version: str, source: str) -> str:
    """Hand-roll a minimal PEP-427 wheel (a zip with dist-info)."""
    name = f"{pkg}-{version}-py3-none-any.whl"
    dist = f"{pkg}-{version}.dist-info"
    wheel_path = os.path.join(path, name)
    records = []

    def add(zf, arcname, data: bytes):
        zf.writestr(arcname, data)
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(data).digest()).rstrip(b"=").decode()
        records.append(f"{arcname},sha256={digest},{len(data)}")

    with zipfile.ZipFile(wheel_path, "w") as zf:
        add(zf, f"{pkg}.py", source.encode())
        add(zf, f"{dist}/METADATA",
            f"Metadata-Version: 2.1\nName: {pkg}\nVersion: {version}\n"
            .encode())
        add(zf, f"{dist}/WHEEL",
            b"Wheel-Version: 1.0\nGenerator: rt-test\nRoot-Is-Purelib: "
            b"true\nTag: py3-none-any\n")
        records.append(f"{dist}/RECORD,,")
        zf.writestr(f"{dist}/RECORD", "\n".join(records) + "\n")
    return wheel_path


def test_materialize_pip_env_offline(tmp_path):
    from ray_tpu.runtime_env import materialize_pip_env

    wheels = tmp_path / "wheels"
    wheels.mkdir()
    _make_wheel(str(wheels), "rt_test_pkg", "1.0.0",
                "MAGIC = 'from-local-wheel'\n")
    site = materialize_pip_env(["rt_test_pkg"], str(wheels))
    assert os.path.exists(os.path.join(site, "rt_test_pkg.py"))
    # Cached: second call returns the same materialized dir instantly.
    assert materialize_pip_env(["rt_test_pkg"], str(wheels)) == site


def test_task_runs_in_pip_runtime_env(rt_init, tmp_path):
    """A task with runtime_env={'pip': [...], 'pip_wheel_dir': ...} can
    import the wheel-only package; tasks WITHOUT the env cannot."""
    import ray_tpu as rt

    wheels = tmp_path / "wheels"
    wheels.mkdir()
    _make_wheel(str(wheels), "rt_env_only_pkg", "2.0.0",
                "VALUE = 41 + 1\n")

    @rt.remote
    def uses_pkg():
        import rt_env_only_pkg

        return rt_env_only_pkg.VALUE

    env = {"pip": ["rt_env_only_pkg"], "pip_wheel_dir": str(wheels)}
    assert rt.get(uses_pkg.options(runtime_env=env).remote(),
                  timeout=120) == 42

    # Isolation contract is PATH-level (like py_modules): tasks without
    # the env never see the materialized site dir on sys.path. (A
    # module-cache hit in a reused worker is possible, as in any shared
    # worker pool, so asserting an ImportError would be flaky.)
    @rt.remote
    def sees_env_path():
        import sys as _sys

        return any("rt_runtime_env" in p for p in _sys.path)

    assert rt.get(sees_env_path.remote(), timeout=60) is False
