"""Runtime integration with the native control-store daemon.

Covers the NativeBackedControlStore hybrid: KV + pubsub + liveness in
C++, actor/job tables in Python, with the full task/actor path running
on top (reference analog: everything talking through gcs_server).
"""

import time

import pytest

from ray_tpu.core.gcs_socket import build_native

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable")


@pytest.fixture()
def native_rt(monkeypatch):
    monkeypatch.setenv("RT_NATIVE_CONTROL_STORE", "1")
    from ray_tpu.core.config import Config

    Config.reset()
    import ray_tpu as rt

    rt.init(num_cpus=2)
    yield rt
    rt.shutdown()
    Config.reset()


def test_runtime_uses_native_store(native_rt):
    from ray_tpu.core.gcs import NativeBackedControlStore
    from ray_tpu.core.runtime import get_runtime

    gcs = get_runtime().gcs
    assert isinstance(gcs, NativeBackedControlStore)
    # KV rides the daemon.
    gcs.kv_put(b"k", b"v")
    assert gcs.kv_get(b"k") == b"v"
    stats = gcs._client.stats()
    assert stats["kv_entries"] >= 1
    assert stats["nodes"] >= 1  # node table dual-written


def test_tasks_and_actors_on_native_store(native_rt):
    rt = native_rt

    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(2, 3)) == 5

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.incr.remote()) == 1
    assert rt.get(c.incr.remote()) == 2


def test_native_pubsub_roundtrip(native_rt):
    from ray_tpu.core.runtime import get_runtime

    gcs = get_runtime().gcs
    got = []
    gcs.pubsub.subscribe("custom-chan", got.append)
    time.sleep(0.05)
    gcs.pubsub.publish("custom-chan", {"payload": [1, 2, 3]})
    deadline = time.monotonic() + 2.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [{"payload": [1, 2, 3]}]


def test_named_actor_lookup_still_works(native_rt):
    rt = native_rt

    @rt.remote
    class Store:
        def get(self):
            return "found"

    Store.options(name="kvstore").remote()
    handle = rt.get_actor("kvstore")
    assert rt.get(handle.get.remote()) == "found"
