"""BC/MARWIL (offline) and TD3 (continuous control).

Reference analogs: ``rllib/algorithms/bc``, ``rllib/algorithms/marwil``,
and the TD3 preset of ``rllib/algorithms/ddpg``. Learning tests follow
the bounded-time reward-threshold pattern
(``rllib/utils/test_utils.py:511``)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rllib import (
    BCConfig,
    MARWILConfig,
    PPOConfig,
    TD3Config,
)
from ray_tpu.rllib.offline import JsonWriter
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    DONES,
    OBS,
    REWARDS,
    SampleBatch,
)


def _make_cartpole_dataset(tmp_path, steps=4000):
    """Expert-ish data: train a quick PPO then log its rollouts."""
    config = (
        PPOConfig()
        .environment("FastCartPole")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                  rollout_fragment_length=32)
        .training(train_batch_size=256, num_sgd_iter=6)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        for _ in range(15):
            algo.train()
        worker = algo.workers.local_worker
        writer = JsonWriter(str(tmp_path))
        logged = 0
        while logged < steps:
            batch = worker.sample(32)
            # Keep the time-major [T, N] shape: flattening would
            # interleave the vector envs' episodes and corrupt the
            # return-to-go computation downstream.
            cols = {
                OBS: np.asarray(batch[OBS]),
                ACTIONS: np.asarray(batch[ACTIONS]),
                REWARDS: np.asarray(batch[REWARDS]),
                DONES: np.asarray(batch[DONES]),
            }
            writer.write(SampleBatch(cols))
            logged += cols[REWARDS].size
        writer.close()
        # The behavior policy's own quality, for the BC bar below.
        stats = worker.episode_stats()
        return stats.get("episode_reward_mean") or 0.0
    finally:
        algo.stop()


@pytest.mark.slow
def test_bc_clones_behavior_policy(tmp_path):
    rt.init(num_cpus=2, ignore_reinit_error=True)
    try:
        behavior_reward = _make_cartpole_dataset(tmp_path)
        assert behavior_reward > 50, (
            f"behavior policy too weak to clone ({behavior_reward})")
        config = (
            BCConfig()
            .environment("FastCartPole")
            .offline_data(str(tmp_path))
            .training(lr=1e-3, train_batch_size=256,
                      num_updates_per_iter=64)
            .debugging(seed=0)
        )
        config.policy_hidden = (64, 64)
        algo = config.build()
        try:
            for _ in range(15):
                result = algo.train()
            assert np.isfinite(result["total_loss"])
            evaluation = algo.evaluate(episodes=5)
            # The clone must reach a sizable fraction of the behavior
            # policy's reward purely from logged data.
            assert evaluation["episode_reward_mean"] >= min(
                100.0, 0.5 * behavior_reward), (behavior_reward,
                                                evaluation)
        finally:
            algo.stop()
    finally:
        rt.shutdown()


def test_marwil_weighting_and_state_roundtrip(tmp_path):
    rt.init(num_cpus=2, ignore_reinit_error=True)
    try:
        # Tiny synthetic dataset: two actions, action 1 always better.
        rng = np.random.default_rng(0)
        obs = rng.normal(size=(512, 4)).astype(np.float32)
        actions = rng.integers(0, 2, 512)
        rewards = np.where(actions == 1, 1.0, 0.0).astype(np.float32)
        # Length-1 episodes: return-to-go == the action's own reward, so
        # the advantage signal is exactly the action quality.
        dones = np.ones(512, bool)
        writer = JsonWriter(str(tmp_path))
        writer.write(SampleBatch({OBS: obs, ACTIONS: actions,
                                  REWARDS: rewards, DONES: dones}))
        writer.close()
        config = (
            MARWILConfig()
            .environment("FastCartPole")
            .offline_data(str(tmp_path))
            .training(beta=1.0, train_batch_size=128,
                      num_updates_per_iter=32)
            .debugging(seed=0)
        )
        config.policy_hidden = (32,)
        algo = config.build()
        try:
            for _ in range(12):
                result = algo.train()
            assert np.isfinite(result["policy_loss"])
            # Advantage weighting must push the policy toward action 1.
            worker = algo.workers.local_worker
            acts, _, _ = worker.policy.compute_actions(
                obs[:128], deterministic=True)
            assert (acts == 1).mean() > 0.8
            state = algo.get_state()
            algo.set_state(state)
        finally:
            algo.stop()
    finally:
        rt.shutdown()


def test_td3_smoke_and_structure():
    rt.init(num_cpus=2, ignore_reinit_error=True)
    try:
        config = (
            TD3Config()
            .environment("FastPendulum")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=8)
            .training(train_batch_size=64, learning_starts=16,
                      num_updates_per_iter=4, policy_delay=2)
            .debugging(seed=0)
        )
        config.policy_hidden = (32, 32)
        algo = config.build()
        try:
            r1 = algo.train()
            r2 = algo.train()
            assert r2["num_learner_updates"] > 0
            assert np.isfinite(r2["critic_loss"])
            # Actions bounded by the env's action space.
            worker = algo.workers.local_worker
            obs = worker.env.vector_reset(seed=1)
            acts, _, _ = worker.policy.compute_actions(obs)
            assert acts.min() >= -2.0 and acts.max() <= 2.0
            state = algo.get_state()
            algo.set_state(state)
        finally:
            algo.stop()
    finally:
        rt.shutdown()


@pytest.mark.slow
def test_td3_pendulum_learns():
    rt.init(num_cpus=2, ignore_reinit_error=True)
    try:
        config = (
            TD3Config()
            .environment("FastPendulum")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                      rollout_fragment_length=8)
            .training(lr=1e-3, train_batch_size=128,
                      learning_starts=1500, num_updates_per_iter=32,
                      tau=0.01, explore_sigma=0.2)
            .debugging(seed=0)
        )
        config.policy_hidden = (64, 64)
        algo = config.build()
        best = -np.inf
        try:
            for _ in range(400):
                result = algo.train()
                r = result.get("episode_reward_mean")
                if r is not None:
                    best = max(best, r)
                if best >= -350.0:
                    break
        finally:
            algo.stop()
        assert best >= -350.0, f"TD3 did not learn pendulum ({best:.0f})"
    finally:
        rt.shutdown()
