"""End-to-end LLM serving: engine replica behind serve.run + the HTTP
proxy, with streamed tokens (VERDICT r4 item 1, SURVEY §7.2 step 9)."""

import json
import urllib.request

import jax
import numpy as np
import pytest


@pytest.fixture()
def serve_instance(rt_shared):
    from ray_tpu import serve

    serve.start(http_port=18571)
    yield serve
    serve.shutdown()


def _reference(prompt, max_new):
    from ray_tpu.models import llama

    cfg = llama.CONFIGS["llama-tiny"]
    params, _ = llama.init_params(jax.random.PRNGKey(0), cfg)
    out = llama.generate(params, np.asarray([prompt], dtype=np.int32),
                         cfg, max_new=max_new)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def test_llm_app_http_and_stream(serve_instance):
    from ray_tpu.llm import build_llm_app

    app = build_llm_app(model="llama-tiny", num_slots=4, chunk=8,
                        seed=0, name="llm")
    serve_instance.run(app)
    prompt = [3, 141, 59, 26, 5]
    ref = _reference(prompt, 10)

    body = json.dumps({"prompt": prompt, "max_tokens": 10}).encode()
    req = urllib.request.Request("http://127.0.0.1:18571/llm", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        out = json.loads(r.read())
    assert out["tokens"] == ref
    assert out["finish_reason"] == "length"
    assert out["prompt_len"] == len(prompt)

    # streamed: chunked transfer, one JSON token per line, same tokens
    body = json.dumps({"prompt": prompt, "max_tokens": 10,
                       "stream": True}).encode()
    req = urllib.request.Request("http://127.0.0.1:18571/llm", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        lines = [ln for ln in r.read().decode().splitlines() if ln]
    assert [json.loads(ln) for ln in lines] == ref


def test_llm_burst_sheds_with_503(serve_instance):
    """A burst beyond slot + pending capacity must shed with typed 503
    ("overloaded") responses while admitted requests complete normally
    — not stall, not 500, not grow the queue without bound."""
    import threading
    import urllib.error

    from ray_tpu.llm import build_llm_app

    app = build_llm_app(model="llama-tiny", num_slots=1, chunk=8,
                        seed=0, name="llmshed", max_pending=1,
                        queue_timeout_s=30.0)
    serve_instance.run(app)
    prompt = [3, 141, 59, 26, 5]
    ref = _reference(prompt, 8)
    results = {}

    def call(i):
        body = json.dumps({"prompt": prompt, "max_tokens": 8}).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:18571/llmshed", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                results[i] = ("ok", json.loads(r.read()))
        except urllib.error.HTTPError as e:
            results[i] = (e.code, json.loads(e.read()))

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    shed = [v for v in results.values() if v[0] == 503]
    ok = [v for v in results.values() if v[0] == "ok"]
    assert len(results) == 8
    assert shed, f"burst of 8 into 1 slot + 1 pending never shed: " \
                 f"{sorted(k for k, _ in results.values())}"
    assert ok, "every request shed — resident sessions starved"
    for _, body in shed:
        assert body.get("overloaded") is True, body
        assert "overloaded" in body["error"].lower(), body
    for _, body in ok:
        assert body["tokens"] == ref
    assert not any(v[0] == 500 for v in results.values()), results


def test_llm_concurrent_http_requests(serve_instance):
    """Several in-flight HTTP generations share the slot pool."""
    import threading

    from ray_tpu.llm import build_llm_app

    app = build_llm_app(model="llama-tiny", num_slots=4, chunk=8,
                        seed=0, name="llm2")
    serve_instance.run(app)
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(1, 512, size=n)]
               for n in (4, 9, 6, 12, 5, 7)]
    outs = {}

    def call(i):
        body = json.dumps({"prompt": prompts[i],
                           "max_tokens": 8}).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:18571/llm2", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            outs[i] = json.loads(r.read())["tokens"]

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, p in enumerate(prompts):
        assert outs[i] == _reference(p, 8), f"request {i} diverged"
