"""Test config: force CPU JAX with 8 virtual devices (multi-chip simulation).

Mirrors the reference's single-machine multi-node testing strategy
(``ray.cluster_utils.Cluster``, SURVEY §4): sharding/collective tests run on
an 8-device CPU mesh exactly as they would over a TPU slice.
"""

import os

# Must be set before any jax import (including transitively via ray_tpu).
# NOTE: the environment pre-sets JAX_PLATFORMS (e.g. to a TPU plugin), so
# overwrite rather than setdefault — tests always run on the virtual
# 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# Env var alone is NOT enough for worker subprocesses on hosts whose site
# hooks force a platform via jax.config.update at interpreter start (the
# axon TPU tunnel does) — worker_main re-applies RT_JAX_PLATFORM after
# those hooks, keeping every test worker on the virtual CPU mesh.
os.environ["RT_JAX_PLATFORM"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The image's sitecustomize imports jax at interpreter startup (before this
# file runs), so the env var alone is too late for THIS process — update the
# live config too. Worker subprocesses get the env var via inheritance.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rt_init():
    """A fresh 1-node runtime per test, torn down after.

    Do not mix with ``rt_shared`` in the same module: this fixture tears the
    process-wide runtime down.
    """
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=4)
    yield rt
    rt.shutdown()


@pytest.fixture(scope="module")
def rt_shared():
    """Module-shared runtime for stateless API tests (fast path).

    Analogous to the reference's ``ray_start_regular_shared``.
    """
    import ray_tpu as rt

    # An earlier module may have left an auto-inited runtime alive with
    # machine-sized num_cpus (=1 on this box) — too small for the gang
    # tests. Always start from a known 4-CPU runtime.
    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=4)
    # Warm two workers so latency-sensitive tests see a hot pool.
    @rt.remote
    def _noop():
        return None

    rt.get([_noop.remote() for _ in range(2)])
    yield rt
    rt.shutdown()


@pytest.fixture
def rt_cluster():
    """Multi-node simulated cluster (one head + helper to add nodes)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield cluster
    cluster.shutdown()
