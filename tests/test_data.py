"""Data library tests (mirrors ``python/ray/data/tests`` coverage)."""

import numpy as np
import pytest

from ray_tpu import data as rd


def test_from_items_count(rt_shared):
    ds = rd.from_items(list(range(100)), parallelism=8)
    assert ds.num_blocks() == 8
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]


def test_map_and_filter(rt_shared):
    ds = rd.range(20, parallelism=4)
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    assert sorted(out.take_all()) == [0, 4, 8, 12, 16, 20, 24, 28, 32, 36]


def test_flat_map(rt_shared):
    ds = rd.from_items([1, 2, 3], parallelism=2)
    assert sorted(ds.flat_map(lambda x: [x, x]).take_all()) == [1, 1, 2, 2, 3, 3]


def test_map_batches_numpy(rt_shared):
    ds = rd.from_numpy(np.arange(32, dtype=np.float32), parallelism=4)
    out = ds.map_batches(
        lambda b: {"data": b["data"] * 10}, batch_format="numpy"
    )
    total = out.to_numpy()
    np.testing.assert_allclose(
        np.sort(total["data"]), np.arange(32, dtype=np.float32) * 10
    )


def test_aggregates(rt_shared):
    ds = rd.from_items([{"a": i} for i in range(10)], parallelism=3)
    assert ds.sum("a") == 45
    assert ds.mean("a") == 4.5
    assert ds.min("a") == 0
    assert ds.max("a") == 9


def test_random_shuffle(rt_shared):
    ds = rd.range(50, parallelism=4)
    shuffled = ds.random_shuffle(seed=0)
    rows = shuffled.take_all()
    assert sorted(rows) == list(range(50))
    assert rows != list(range(50))


def test_sort_and_groupby(rt_shared):
    ds = rd.from_items(
        [{"k": i % 3, "v": i} for i in range(9)], parallelism=3
    )
    s = ds.sort(key="v", descending=True).take(3)
    assert [r["v"] for r in s] == [8, 7, 6]
    counts = {r["key"]: r["count"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 3, 1: 3, 2: 3}


def test_split_for_ranks(rt_shared):
    ds = rd.range(40, parallelism=4)
    shards = ds.split(2)
    assert len(shards) == 2
    all_rows = sorted(shards[0].take_all() + shards[1].take_all())
    assert all_rows == list(range(40))


def test_repartition(rt_shared):
    ds = rd.range(30, parallelism=2).repartition(6)
    assert ds.num_blocks() == 6
    assert sorted(ds.take_all()) == list(range(30))


def test_iter_batches(rt_shared):
    ds = rd.from_numpy(np.arange(100), parallelism=5)
    batches = list(ds.iter_batches(batch_size=32, batch_format="numpy"))
    sizes = [len(b["data"]) for b in batches]
    assert sum(sizes) == 100
    assert all(s == 32 for s in sizes[:-1])


def test_to_jax(rt_shared):
    import jax

    ds = rd.from_numpy(np.arange(64, dtype=np.float32), parallelism=4)
    batches = list(ds.to_jax(batch_size=16))
    assert len(batches) == 4
    assert isinstance(batches[0]["data"], jax.Array)


def test_csv_roundtrip(rt_shared, tmp_path):
    ds = rd.from_items(
        [{"x": i, "y": i * 1.5} for i in range(20)], parallelism=2
    )
    paths = rd.CSVDatasource().write(ds, str(tmp_path / "csvs"))
    assert len(paths) == 2
    back = rd.read_csv(str(tmp_path / "csvs"))
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert rows[3] == {"x": 3, "y": 4.5}


def test_json_roundtrip(rt_shared, tmp_path):
    ds = rd.from_items([{"a": i} for i in range(10)], parallelism=2)
    rd.JSONDatasource().write(ds, str(tmp_path / "js"))
    back = rd.read_json(str(tmp_path / "js"))
    assert sorted(r["a"] for r in back.take_all()) == list(range(10))


def test_pipeline_windows(rt_shared):
    ds = rd.from_numpy(np.arange(40), parallelism=8)
    pipe = ds.window(blocks_per_window=2).map_batches(
        lambda b: {"data": b["data"] + 1}, batch_format="numpy"
    )
    rows = [int(r["data"]) for r in pipe.iter_rows()]
    assert sorted(rows) == list(range(1, 41))


def test_pipeline_repeat_epochs(rt_shared):
    ds = rd.range(10, parallelism=2)
    pipe = ds.repeat(3)
    assert len(pipe.take(30)) == 30


def test_actor_pool_compute(rt_shared):
    ds = rd.from_numpy(np.arange(16), parallelism=4)
    out = ds.map_batches(
        lambda b: {"data": np.asarray(b["data"]) * 2},
        batch_format="numpy", compute="actors",
    )
    assert sorted(int(x) for x in out.to_numpy()["data"]) == [
        i * 2 for i in range(16)
    ]


def test_lazy_plan_fuses_map_stages(rt_shared):
    """map_batches().map().filter() executes as ONE task per block
    (reference: ExecutionPlan stage fusion, _internal/plan.py:69)."""
    import ray_tpu.data as rtd
    from ray_tpu.core.runtime import get_head_runtime

    ds = rtd.from_items(list(range(64)), parallelism=4)
    chained = (ds
               .map_batches(lambda b: [x * 2 for x in b],
                            batch_format="native")
               .map(lambda r: r + 1)
               .filter(lambda r: r % 4 == 1))
    # nothing executed yet
    assert chained._plan._executed is None
    assert len(chained._plan.stages) == 3

    head = get_head_runtime()
    before = len(head._tasks)
    out = sorted(chained.take_all())
    submitted = len(head._tasks) - before
    assert submitted == 4, f"expected 4 fused tasks, saw {submitted}"
    assert out == sorted(x * 2 + 1 for x in range(64) if (x * 2 + 1) % 4 == 1)


def test_shuffle_no_single_task_concat(rt_shared):
    """random_shuffle runs as split tasks + per-output-block reduce tasks
    (two-stage map/reduce, reference push_based_shuffle) — no task ever
    sees the whole dataset."""
    import ray_tpu.data as rtd
    from ray_tpu.core.runtime import get_head_runtime

    ds = rtd.from_items(list(range(400)), parallelism=8)
    _ = ds._blocks  # materialize input
    head = get_head_runtime()
    before = len(head._tasks)
    shuffled = ds.random_shuffle(seed=7)
    out = shuffled.take_all()
    submitted = len(head._tasks) - before
    assert sorted(out) == list(range(400))
    # 8 split tasks + 8 reduce tasks (+ take fetches, no monolithic concat)
    assert submitted >= 16
    assert shuffled.num_blocks() == 8


def test_parquet_row_group_parallelism(rt_shared, tmp_path):
    """One read task per parquet row group, not per file."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    import ray_tpu.data as rtd

    df = pd.DataFrame({"x": range(100), "y": [i * 0.5 for i in range(100)]})
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.Table.from_pandas(df), path, row_group_size=25)
    assert pq.ParquetFile(path).metadata.num_row_groups == 4

    ds = rtd.read_parquet(path)
    assert ds.num_blocks() == 4  # one block per row group from ONE file
    assert ds.count() == 100
    total = ds.sum(on="x")
    assert total == sum(range(100))


def test_restructure_ops_never_materialize_on_driver(rt_init, monkeypatch):
    """sort/split/groupby/repartition must run as task waves — the
    driver-side take_all() path is forbidden (reference: distributed
    sample-sort ``_internal/sort.py`` + push-based shuffle; VERDICT r2
    Weak #6). take_all is patched to explode during the transforms."""
    import ray_tpu.data.dataset as dmod
    from ray_tpu.data import from_items

    rows = [{"k": f"key-{i % 7}", "v": (i * 37) % 101} for i in range(120)]
    ds = from_items(rows, parallelism=6)

    def boom(self):
        raise AssertionError("transform materialized rows on the driver")

    monkeypatch.setattr(dmod.Dataset, "take_all", boom)
    sorted_ds = ds.sort(key="v")
    counted = ds.groupby("k").count()
    agg = ds.groupby("k").aggregate(lambda v: sum(r["v"] for r in v))
    shards = ds.split(7, equal=True)  # 6 blocks / 7 shards -> slice path
    repart = ds.repartition(3)
    monkeypatch.undo()

    got = [r["v"] for r in sorted_ds.iter_rows()]
    assert got == sorted(r["v"] for r in rows)
    assert sum(len(list(s.iter_rows())) for s in shards) == len(rows)
    sizes = [len(list(s.iter_rows())) for s in shards]
    assert max(sizes) - min(sizes) <= 1  # equalized
    assert repart.num_blocks() == 3
    assert sorted(r["v"] for r in repart.iter_rows()) == sorted(
        r["v"] for r in rows)

    by_key = {}
    for r in rows:
        by_key[r["k"]] = by_key.get(r["k"], 0) + 1
    got_counts = {r["key"]: r["count"] for r in counted.iter_rows()}
    assert got_counts == by_key
    want_sums = {}
    for r in rows:
        want_sums[r["k"]] = want_sums.get(r["k"], 0) + r["v"]
    got_sums = {r["key"]: r["value"] for r in agg.iter_rows()}
    assert got_sums == want_sums
