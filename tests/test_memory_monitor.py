"""Memory monitor tests: sampling, threshold policy, runtime integration.

Reference coverage analog: memory_monitor_test.cc + the raylet
worker-killing policy tests.
"""

import time

import numpy as np
import pytest

from ray_tpu.core.memory_monitor import (
    MemoryMonitor,
    MemorySnapshot,
    sample_memory,
)


def test_sample_memory_sane():
    snap = sample_memory()
    assert snap.total_bytes > 0
    assert 0 < snap.used_bytes <= snap.total_bytes
    assert 0.0 < snap.fraction < 1.0


def test_threshold_callback_fires_with_refractory():
    fired = []
    mon = MemoryMonitor(threshold=0.0,  # every poll is "high"
                        on_high=fired.append,
                        min_callback_interval_s=10.0)
    mon.poll_once()
    mon.poll_once()  # inside refractory window: suppressed
    assert len(fired) == 1
    assert isinstance(fired[0], MemorySnapshot)


def test_callback_not_fired_below_threshold():
    fired = []
    mon = MemoryMonitor(threshold=1.1, on_high=fired.append)
    mon.poll_once()
    assert fired == []


def test_monitor_thread_start_stop():
    mon = MemoryMonitor(threshold=1.1, period_s=0.01)
    mon.start()
    time.sleep(0.1)
    assert mon.last_snapshot is not None
    mon.stop()


def test_pressure_policy_kills_newest_retriable_task(rt_init):
    """Simulated pressure: the policy must kill a running retriable task's
    worker and the task must complete via retry."""
    rt = rt_init

    @rt.remote(max_retries=3)
    def slow(x):
        time.sleep(1.5)
        return x * 2

    refs = [slow.remote(i) for i in range(2)]
    time.sleep(0.6)  # let tasks reach RUNNING
    from ray_tpu.core.runtime import get_runtime

    runtime = get_runtime()
    runtime._on_memory_pressure(MemorySnapshot(99, 100))
    # Tasks still finish (killed one retried).
    assert rt.get(refs, timeout=30) == [0, 2]
