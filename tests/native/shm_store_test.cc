// C++ unit tests for the shm arena store — compiled and run by
// tests/test_native_unit.py with ASan/UBSan, and again with TSan for
// the concurrent sections (SURVEY §4.5: the daemons' concurrency story
// must not rest on Python end-to-end tests alone).
//
// Includes the store's translation unit directly: the C ABI is the
// contract under test and the single-TU layout keeps the build one
// g++ invocation.

#include "../../ray_tpu/_native/shm_store.cc"

#include <array>
#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <vector>

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,      \
                   __LINE__, #cond);                                     \
      std::exit(1);                                                      \
    }                                                                    \
  } while (0)

namespace {

std::string unique_name(const char* tag) {
  return std::string("/rt_cc_test_") + tag + "_" +
         std::to_string(::getpid());
}

void make_key(uint8_t* out, uint32_t i, uint32_t salt = 0) {
  std::memset(out, 0, kKeySize);
  std::memcpy(out, &i, sizeof(i));
  std::memcpy(out + sizeof(i), &salt, sizeof(salt));
}

void test_put_get_delete_roundtrip() {
  auto name = unique_name("basic");
  void* h = rt_store_create(name.c_str(), 1 << 20);
  CHECK(h != nullptr);
  uint8_t key[kKeySize];
  make_key(key, 1);
  std::vector<uint8_t> payload(1000, 0xAB);
  CHECK(rt_store_put(h, key, payload.data(), payload.size()) == 0);
  CHECK(rt_store_put(h, key, payload.data(), payload.size()) == -1);
  uint64_t size = 0;
  const uint8_t* ptr = rt_store_get(h, key, &size);
  CHECK(ptr != nullptr && size == payload.size());
  CHECK(std::memcmp(ptr, payload.data(), size) == 0);
  rt_store_release(h, key);
  CHECK(rt_store_delete(h, key) == 0);
  CHECK(rt_store_get(h, key, &size) == nullptr);
  rt_store_close(h, 1);
}

void test_alloc_free_coalescing() {
  auto name = unique_name("coalesce");
  const uint64_t cap = 1 << 20;
  void* h = rt_store_create(name.c_str(), cap);
  uint64_t c0, used0, n0;
  rt_store_stats(h, &c0, &used0, &n0);
  CHECK(used0 == 0);
  // Fill with many objects, free in interleaved order, then a single
  // allocation spanning nearly the whole arena must succeed — proof
  // the free list coalesced back to one extent.
  std::vector<std::array<uint8_t, kKeySize>> keys(64);
  std::vector<uint8_t> payload(8 * 1024, 1);
  for (uint32_t i = 0; i < 64; i++) {
    make_key(keys[i].data(), i, 7);
    CHECK(rt_store_put(h, keys[i].data(), payload.data(),
                       payload.size()) == 0);
  }
  for (uint32_t i = 0; i < 64; i += 2) rt_store_delete(h, keys[i].data());
  for (uint32_t i = 1; i < 64; i += 2) rt_store_delete(h, keys[i].data());
  uint64_t c1, used1, n1;
  rt_store_stats(h, &c1, &used1, &n1);
  CHECK(used1 == 0 && n1 == 0);
  uint8_t big_key[kKeySize];
  make_key(big_key, 9999);
  std::vector<uint8_t> big(cap - 4096, 2);
  CHECK(rt_store_put(h, big_key, big.data(), big.size()) == 0);
  rt_store_close(h, 1);
}

void test_pin_deferred_free() {
  auto name = unique_name("pin");
  void* h = rt_store_create(name.c_str(), 1 << 20);
  uint8_t key[kKeySize];
  make_key(key, 5);
  std::vector<uint8_t> payload(512, 0x5A);
  CHECK(rt_store_put(h, key, payload.data(), payload.size()) == 0);
  uint64_t size = 0;
  const uint8_t* ptr = rt_store_get(h, key, &size);  // pin
  CHECK(ptr != nullptr);
  CHECK(rt_store_delete(h, key) == 1);  // deferred: reader still pinned
  // The extent's bytes must remain intact while pinned.
  CHECK(std::memcmp(ptr, payload.data(), size) == 0);
  // New put under the same key must refuse while the old extent lives.
  CHECK(rt_store_put(h, key, payload.data(), payload.size()) == -5);
  rt_store_release(h, key);  // last pin -> extent actually freed
  CHECK(rt_store_put(h, key, payload.data(), payload.size()) == 0);
  rt_store_close(h, 1);
}

void test_create_seal_abort() {
  auto name = unique_name("seal");
  void* h = rt_store_create(name.c_str(), 1 << 20);
  uint8_t key[kKeySize];
  make_key(key, 11);
  int32_t err = 0;
  uint8_t* w = rt_store_create_object(h, key, 256, &err);
  CHECK(w != nullptr && err == 0);
  // Unsealed reservation blocks a second writer with -6.
  uint8_t* w2 = rt_store_create_object(h, key, 256, &err);
  CHECK(w2 == nullptr && err == -6);
  std::memset(w, 0xCC, 256);
  CHECK(rt_store_seal(h, key) == 0);
  uint64_t size = 0;
  const uint8_t* r = rt_store_get(h, key, &size);
  CHECK(r != nullptr && size == 256 && r[0] == 0xCC);
  rt_store_release(h, key);
  // Abort path: reserve then abort frees the extent.
  uint8_t key2[kKeySize];
  make_key(key2, 12);
  w = rt_store_create_object(h, key2, 128, &err);
  CHECK(w != nullptr);
  CHECK(rt_store_abort(h, key2) == 0);
  CHECK(rt_store_get(h, key2, &size) == nullptr);
  rt_store_close(h, 1);
}

void test_repair_after_torn_state() {
  auto name = unique_name("repair");
  const uint64_t cap = 1 << 20;
  void* h = rt_store_create(name.c_str(), cap);
  uint8_t survivor[kKeySize];
  make_key(survivor, 21);
  std::vector<uint8_t> payload(4096, 0x77);
  CHECK(rt_store_put(h, survivor, payload.data(), payload.size()) == 0);
  // Simulate a writer dying mid-allocation from a SECOND attachment
  // (its exit leaves the mutex OWNER_DIED and the state torn).
  void* h2 = rt_store_attach(name.c_str());
  CHECK(h2 != nullptr);
  uint8_t torn[kKeySize];
  make_key(torn, 22);
  std::thread([&] {
    CHECK(rt_store_test_die_mid_alloc(h2, torn) == 0);
    // Thread exits holding the robust mutex -> OWNER_DIED.
  }).join();
  // Next lock on the first handle repairs: survivor intact, torn slot
  // tombstoned, free list rebuilt so a big allocation still works.
  uint64_t size = 0;
  const uint8_t* r = rt_store_get(h, survivor, &size);
  CHECK(r != nullptr && size == payload.size());
  CHECK(std::memcmp(r, payload.data(), size) == 0);
  rt_store_release(h, survivor);
  uint64_t c, used, n;
  rt_store_stats(h, &c, &used, &n);
  CHECK(n == 1);
  uint8_t big_key[kKeySize];
  make_key(big_key, 23);
  std::vector<uint8_t> big(cap / 2, 3);
  CHECK(rt_store_put(h, big_key, big.data(), big.size()) == 0);
  rt_store_close(h2, 0);
  rt_store_close(h, 1);
}

void test_repair_prefers_sealed_pinned() {
  // A torn CREATED slot forged at a LOWER offset overlapping a pinned
  // SEALED object must LOSE repair: overlap resolution ranks SEALED
  // above CREATED regardless of offset order, so the live object stays
  // readable and its bytes never return to the free list while a
  // reader holds a zero-copy view.
  auto name = unique_name("repair_rank");
  const uint64_t cap = 1 << 20;
  void* h = rt_store_create(name.c_str(), cap);
  Store* s = static_cast<Store*>(h);
  uint8_t sealed_key[kKeySize];
  make_key(sealed_key, 31);
  std::vector<uint8_t> payload(4096, 0x5a);
  CHECK(rt_store_put(h, sealed_key, payload.data(), payload.size()) == 0);
  uint64_t size = 0;
  const uint8_t* pinned = rt_store_get(h, sealed_key, &size);  // pin
  CHECK(pinned != nullptr && size == payload.size());
  pthread_mutex_lock(&header(s)->mutex);
  Slot* victim = find_slot(s, sealed_key, false);
  CHECK(victim != nullptr && victim->state == SLOT_SEALED);
  uint8_t torn_key[kKeySize];
  make_key(torn_key, 32);
  Slot* torn = find_slot(s, torn_key, true);
  std::memcpy(torn->key, torn_key, kKeySize);
  torn->state = SLOT_CREATED;
  torn->offset = victim->offset >= kAlign ? victim->offset - kAlign : 0;
  torn->alloc_size = victim->alloc_size + 2 * kAlign;  // spans victim
  torn->size = torn->alloc_size;
  torn->refcount = 0;
  repair_store(s);
  pthread_mutex_unlock(&header(s)->mutex);
  CHECK(victim->state == SLOT_SEALED);
  CHECK(torn->state == SLOT_TOMBSTONE);
  CHECK(std::memcmp(pinned, payload.data(), payload.size()) == 0);
  // Churn the arena hard: if repair had leaked the pinned extent to the
  // free list, one of these writes would land on top of it.
  std::vector<uint8_t> filler(32 * 1024, 0xee);
  for (uint32_t i = 0; i < 256; i++) {
    uint8_t k[kKeySize];
    make_key(k, i, 99);
    int rc = rt_store_put(h, k, filler.data(), filler.size());
    CHECK(rc == 0 || rc == -2);  // ok or arena full
    if (rc == 0 && (i & 1)) rt_store_delete(h, k);
  }
  CHECK(std::memcmp(pinned, payload.data(), payload.size()) == 0);
  rt_store_release(h, sealed_key);
  rt_store_close(h, 1);
}

void test_repair_pinned_loser_stays_reserved() {
  // When a PINNED slot loses overlap resolution (forged SEALED extent
  // overlapping a real SEALED winner at a lower offset), its bytes must
  // stay reserved: the surviving reader's release tombstones the slot
  // WITHOUT returning the conflicted bytes to the allocator.
  auto name = unique_name("repair_pin");
  const uint64_t cap = 1 << 20;
  void* h = rt_store_create(name.c_str(), cap);
  Store* s = static_cast<Store*>(h);
  uint8_t winner_key[kKeySize];
  make_key(winner_key, 41);
  std::vector<uint8_t> payload(4096, 0x21);
  CHECK(rt_store_put(h, winner_key, payload.data(), payload.size()) == 0);
  pthread_mutex_lock(&header(s)->mutex);
  Slot* winner = find_slot(s, winner_key, false);
  CHECK(winner != nullptr);
  // Forge a pinned SEALED slot whose extent sits INSIDE the winner's.
  uint8_t loser_key[kKeySize];
  make_key(loser_key, 42);
  Slot* loser = find_slot(s, loser_key, true);
  std::memcpy(loser->key, loser_key, kKeySize);
  loser->state = SLOT_SEALED;
  loser->offset = winner->offset + kAlign;
  loser->alloc_size = kAlign;
  loser->size = kAlign;
  loser->refcount = 1;  // a surviving reader maps it
  uint64_t resv_off = loser->offset;
  uint64_t resv_size = loser->alloc_size;
  repair_store(s);
  pthread_mutex_unlock(&header(s)->mutex);
  CHECK(winner->state == SLOT_SEALED);
  CHECK(loser->state == SLOT_PENDING_DELETE);
  CHECK(loser->alloc_size == 0);  // release must not arena_free
  CHECK(header(s)->reserved_count == 1);  // persisted reservation
  uint64_t c0, used0, n0;
  rt_store_stats(h, &c0, &used0, &n0);
  CHECK(rt_store_release(h, loser_key) == 0);  // reader lets go
  uint64_t c1, used1, n1;
  rt_store_stats(h, &c1, &used1, &n1);
  CHECK(used1 == used0);  // conflicted bytes were NOT refreed
  CHECK(loser->state == SLOT_TOMBSTONE);
  // Winner data survives arena churn after the release.
  uint64_t size = 0;
  const uint8_t* r = rt_store_get(h, winner_key, &size);
  CHECK(r != nullptr && size == payload.size());
  std::vector<uint8_t> filler(32 * 1024, 0xcc);
  for (uint32_t i = 0; i < 64; i++) {
    uint8_t k[kKeySize];
    make_key(k, i, 123);
    int rc = rt_store_put(h, k, filler.data(), filler.size());
    CHECK(rc == 0 || rc == -2);
  }
  CHECK(std::memcmp(r, payload.data(), payload.size()) == 0);
  rt_store_release(h, winner_key);
  // Now the hard case: deleting the WINNER frees its extent but must
  // CLIP the reserved subrange — a surviving reader of the loser still
  // maps those bytes, and the allocator may never hand them out again.
  const uint8_t* resv_view =
      reinterpret_cast<const uint8_t*>(arena(s) + resv_off);
  std::vector<uint8_t> before(resv_view, resv_view + resv_size);
  CHECK(rt_store_delete(h, winner_key) == 0);
  for (uint32_t i = 0; i < 256; i++) {
    uint8_t k[kKeySize];
    make_key(k, i, 321);
    int rc = rt_store_put(h, k, filler.data(), filler.size());
    CHECK(rc == 0 || rc == -2);
    if (rc == 0 && (i & 1)) rt_store_delete(h, k);
  }
  CHECK(std::memcmp(resv_view, before.data(), resv_size) == 0);
  rt_store_close(h, 1);
}

void test_concurrent_hammer() {
  // The TSan target: N threads over one arena doing put/get/delete on
  // overlapping key ranges; invariants checked at the end.
  auto name = unique_name("hammer");
  void* h = rt_store_create(name.c_str(), 8 << 20);
  const int kThreads = 4;
  const uint32_t kIters = 300;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back([&, t] {
      std::vector<uint8_t> payload(2048, static_cast<uint8_t>(t));
      for (uint32_t i = 0; i < kIters; i++) {
        uint8_t key[kKeySize];
        make_key(key, i % 37, t);  // per-thread key space + churn
        int rc = rt_store_put(h, key, payload.data(), payload.size());
        if (rc != 0 && rc != -1 && rc != -5) failures.fetch_add(1);
        uint64_t size = 0;
        const uint8_t* ptr = rt_store_get(h, key, &size);
        if (ptr != nullptr) {
          if (size != payload.size() || ptr[0] != static_cast<uint8_t>(t))
            failures.fetch_add(1);
          rt_store_release(h, key);
        }
        rt_store_delete(h, key);
      }
    });
  }
  for (auto& th : threads) th.join();
  CHECK(failures.load() == 0);
  // Phase 2: ALL threads on the SAME key — concurrent put vs pinned
  // get vs deferred delete exercises the per-key state machine
  // (SEALED / PENDING_DELETE / -1 / -5 transitions), not just the
  // allocator mutex.
  std::vector<std::thread> contenders;
  for (int t = 0; t < kThreads; t++) {
    contenders.emplace_back([&] {
      std::vector<uint8_t> payload(1024, 0xEE);
      uint8_t key[kKeySize];
      make_key(key, 424242);
      for (uint32_t i = 0; i < kIters; i++) {
        int rc = rt_store_put(h, key, payload.data(), payload.size());
        if (rc != 0 && rc != -1 && rc != -5) failures.fetch_add(1);
        uint64_t size = 0;
        const uint8_t* ptr = rt_store_get(h, key, &size);
        if (ptr != nullptr) {
          // A pinned extent must stay intact even if another thread
          // deletes the key (deferred free).
          if (size != payload.size() || ptr[0] != 0xEE)
            failures.fetch_add(1);
          rt_store_release(h, key);
        }
        rt_store_delete(h, key);
      }
    });
  }
  for (auto& th : contenders) th.join();
  CHECK(failures.load() == 0);
  uint64_t c, used, n;
  rt_store_stats(h, &c, &used, &n);
  CHECK(n == 0 && used == 0);  // everything deleted, nothing leaked
  rt_store_close(h, 1);
}

}  // namespace

int main() {
  test_put_get_delete_roundtrip();
  test_alloc_free_coalescing();
  test_pin_deferred_free();
  test_create_seal_abort();
  test_repair_after_torn_state();
  test_repair_prefers_sealed_pinned();
  test_repair_pinned_loser_stays_reserved();
  test_concurrent_hammer();
  std::printf("shm_store_test: all OK\n");
  return 0;
}
