"""Declarative Serve config: schema validation, apply, and the
`rt serve` CLI (reference: serve/schema.py ServeApplicationSchema +
serve/scripts.py `serve deploy/config`)."""

import json
import subprocess
import sys

import pytest

from ray_tpu.serve.schema import (
    DeploymentSchema,
    ServeDeploySchema,
)

_APP_MODULE = """
from ray_tpu import serve


@serve.deployment(name="echo", num_replicas=1, route_prefix="/echo")
class Echo:
    def __init__(self, prefix="echo"):
        self.prefix = prefix
        self.scale = 1

    def reconfigure(self, user_config):
        self.scale = user_config.get("scale", 1)

    def __call__(self, request=None):
        return {"who": self.prefix, "scale": self.scale}


app = Echo.bind(prefix="from-config")
"""


def test_schema_validation(tmp_path):
    cfg = {
        "http_options": {"host": "127.0.0.1", "port": 8123},
        "applications": [
            {"import_path": "myapp:app", "name": "a1",
             "deployments": [{"name": "echo", "num_replicas": 2}]},
        ],
    }
    schema = ServeDeploySchema.from_dict(cfg)
    assert schema.http_options.port == 8123
    assert schema.applications[0].deployments[0].num_replicas == 2

    with pytest.raises(ValueError, match="import_path"):
        ServeDeploySchema.from_dict({"applications": [{"name": "x"}]})
    with pytest.raises(ValueError, match="module.sub:attribute"):
        ServeDeploySchema.from_dict(
            {"applications": [{"import_path": "nocolon"}]})
    with pytest.raises(ValueError, match="unknown deployment"):
        DeploymentSchema.from_dict({"name": "d", "replicas": 3})
    with pytest.raises(ValueError, match="non-empty"):
        ServeDeploySchema.from_dict({"applications": []})


def test_schema_from_yaml_file(tmp_path):
    path = tmp_path / "serve.yaml"
    path.write_text(
        "http_options:\n  port: 8222\n"
        "applications:\n"
        "  - import_path: mod:app\n"
        "    name: main\n"
        "    deployments:\n"
        "      - name: echo\n"
        "        num_replicas: 3\n"
    )
    schema = ServeDeploySchema.from_file(str(path))
    assert schema.http_options.port == 8222
    assert schema.applications[0].deployments[0].num_replicas == 3


def test_apply_deploys_and_reconfigures(tmp_path, rt_init):
    (tmp_path / "cfg_app_mod.py").write_text(_APP_MODULE)
    sys.path.insert(0, str(tmp_path))
    try:
        from ray_tpu import serve
        from ray_tpu.serve import schema as serve_schema

        cfg = ServeDeploySchema.from_dict({
            "http_options": {"port": 18431},
            "applications": [{
                "import_path": "cfg_app_mod:app",
                "name": "main",
                "deployments": [
                    {"name": "echo", "num_replicas": 1,
                     "user_config": {"scale": 7}},
                ],
            }],
        })
        deployed = serve_schema.apply(cfg)
        assert deployed["main"]["deployment"] == "echo"
        handle = serve.get_deployment_handle("echo")
        from ray_tpu.core import get

        out = get(handle.remote(), timeout=30)
        assert out == {"who": "from-config", "scale": 7}
        # status surface
        status = serve_schema.status()
        assert status["running"] and "echo" in status["deployments"]
        # Re-apply is idempotent (reconciles, does not error).
        serve_schema.apply(cfg)
        serve.shutdown()
    finally:
        sys.path.remove(str(tmp_path))


def test_cli_serve_config_validates(tmp_path):
    path = tmp_path / "serve.yaml"
    path.write_text(
        "applications:\n  - import_path: mod:app\n    name: m\n")
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "serve", "config",
         str(path)],
        capture_output=True, text=True, cwd="/root/repo", timeout=120)
    assert out.returncode == 0, out.stderr
    parsed = json.loads(out.stdout)
    assert parsed["applications"][0]["import_path"] == "mod:app"

    bad = tmp_path / "bad.yaml"
    bad.write_text("applications:\n  - name: missing-path\n")
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "serve", "config",
         str(bad)],
        capture_output=True, text=True, cwd="/root/repo", timeout=120)
    assert out.returncode != 0
