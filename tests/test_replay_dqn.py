"""Replay buffer unit tests + DQN smoke/learning tests.

Mirrors reference coverage: rllib/utils/replay_buffers/tests/ and
rllib/algorithms/dqn/tests/test_dqn.py.
"""

import numpy as np
import pytest


def _batch(n, start=0):
    from ray_tpu.rllib.sample_batch import SampleBatch

    ids = np.arange(start, start + n)
    return SampleBatch({
        "obs": np.stack([ids, ids], axis=1).astype(np.float32),
        "id": ids.astype(np.int64),
    })


def test_fifo_replay_wraps_and_samples():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=8, seed=0)
    buf.add(_batch(5))
    assert len(buf) == 5
    buf.add(_batch(5, start=5))  # wraps: rows 0,1 overwritten
    assert len(buf) == 8
    assert buf.added_count == 10
    sample = buf.sample(32)
    assert sample["id"].shape == (32,)
    # Overwritten rows 0 and 1 must be gone.
    assert set(sample["id"]).issubset(set(range(2, 10)))


def test_fifo_replay_oversized_add_keeps_newest():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=4, seed=0)
    buf.add(_batch(10))
    sample = buf.sample(64)
    assert set(sample["id"]).issubset({6, 7, 8, 9})


def test_sum_tree_prefix_sampling():
    from ray_tpu.rllib.replay_buffers import SumSegmentTree

    tree = SumSegmentTree(4)
    tree[np.array([0, 1, 2, 3])] = np.array([1.0, 2.0, 3.0, 4.0])
    assert tree.sum() == 10.0
    # Prefix masses map onto leaves proportionally to the weights.
    idx = tree.find_prefixsum_idx(np.array([0.5, 1.5, 3.5, 9.9]))
    np.testing.assert_array_equal(idx, [0, 1, 2, 3])


def test_prioritized_replay_bias_and_updates():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    buf.add(_batch(64))
    # Crank one row's priority way up: it should dominate samples.
    buf.update_priorities(np.array([7]), np.array([1000.0]))
    sample = buf.sample(256, beta=0.4)
    frac = float(np.mean(sample["id"] == 7))
    assert frac > 0.5, f"priority-7 row sampled only {frac:.0%}"
    assert sample["weights"].min() > 0
    # The boosted row is most probable -> smallest IS weight.
    assert sample["weights"][sample["id"] == 7].max() <= 1.0 + 1e-6


def test_reservoir_buffer_uniform_over_stream():
    from ray_tpu.rllib import ReservoirReplayBuffer

    buf = ReservoirReplayBuffer(capacity=32, seed=0)
    buf.add(_batch(1000))
    assert len(buf) == 32
    assert buf.added_count == 1000
    sample = buf.sample(100)
    # Retained rows should span the stream, not just the head.
    assert sample["id"].max() > 500


def test_multi_agent_replay_routes_by_policy():
    from ray_tpu.rllib import MultiAgentReplayBuffer

    buf = MultiAgentReplayBuffer(capacity=16)
    buf.add(_batch(4), policy_id="a")
    buf.add(_batch(8, start=100), policy_id="b")
    assert buf.stats()["a"]["size"] == 4
    assert set(buf.sample(16, policy_id="b")["id"]) <= set(range(100, 108))


def test_dqn_single_iteration(rt_shared):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=32)
            .training(train_batch_size=32, learning_starts=64,
                      num_updates_per_iter=2)
            .build())
    r1 = algo.train()
    assert r1["timesteps_this_iter"] == 128
    assert r1["replay_buffer_size"] == 128
    r2 = algo.train()
    assert r2["num_learner_updates"] == 4  # buffer warm after iter 1
    assert np.isfinite(r2["loss"])
    assert 0.0 < r2["epsilon"] <= 1.0
    algo.stop()


def test_dqn_save_restore(rt_shared, tmp_path):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("FastCartPole")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
            .training(learning_starts=32, num_updates_per_iter=1)
            .build())
    algo.train()
    path = algo.save(str(tmp_path))
    w0 = np.asarray(algo.params["q_w"])
    algo.stop()

    algo2 = (DQNConfig()
             .environment("FastCartPole")
             .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
             .training(learning_starts=32, num_updates_per_iter=1)
             .build())
    algo2.restore(path)
    np.testing.assert_allclose(w0, np.asarray(algo2.params["q_w"]))
    algo2.stop()


@pytest.mark.slow
def test_dqn_learns_cartpole(rt_shared):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                      rollout_fragment_length=32)
            .training(lr=1e-3, train_batch_size=128, learning_starts=500,
                      num_updates_per_iter=32, epsilon_timesteps=5000,
                      target_network_update_freq=100)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(30):
        result = algo.train()
        r = result.get("episode_reward_mean")
        if r is not None:
            best = max(best, r)
        if best >= 100:
            break
    algo.stop()
    assert best >= 100, f"DQN failed to learn CartPole (best={best})"
