"""Workers must land on the SAME JAX backend as the driver.

Round-3 regression twin: the multichip dryrun drove Trainer workers that
silently initialized the real TPU backend while the driver ran on a
virtual 8-device CPU mesh (jax.config.update is process-local; on axon
hosts the site hook force-sets the platform in every child process, so
even an inherited JAX_PLATFORMS env var is overridden). worker_main now
re-applies RT_JAX_PLATFORM after site hooks; this test fails on any host
where a spawned worker still resolves a different backend than the
driver (reference analog: ``python/ray/cluster_utils.py`` Cluster
fixtures asserting homogeneous worker environments).
"""

import jax

import ray_tpu as rt


def _probe_backend():
    import jax

    return {
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }


def test_worker_backend_matches_driver(rt_init):
    probe = rt.remote(_probe_backend)
    out = rt.get(probe.remote())
    assert out["backend"] == jax.default_backend(), (
        f"worker initialized backend {out['backend']!r} but driver runs "
        f"on {jax.default_backend()!r} — RT_JAX_PLATFORM did not reach "
        "the worker (r3 multichip regression)")
    # The virtual-device flag must reach workers through os.environ too:
    # a worker on the right platform but with 1 device still breaks
    # every multi-device mesh build.
    assert out["n_devices"] == len(jax.devices()), (
        f"worker sees {out['n_devices']} devices, driver "
        f"{len(jax.devices())}")


def test_worker_backend_matches_driver_in_actor(rt_init):
    @rt.remote
    class Probe:
        def backend(self):
            return _probe_backend()

    a = Probe.remote()
    out = rt.get(a.backend.remote())
    assert out["backend"] == jax.default_backend()
    assert out["n_devices"] == len(jax.devices())
