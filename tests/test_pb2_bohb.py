"""PB2 (GP-UCB population-based bandits) + BOHB searcher tests
(reference: tune/tests/test_trial_scheduler_pbt.py PB2 cases,
tune/tests/test_searchers.py BOHB cases)."""

import time
from dataclasses import dataclass, field
from typing import Dict

import numpy as np
import pytest


@dataclass
class FakeTrial:
    trial_id: str
    config: Dict
    rungs_passed: Dict = field(default_factory=dict)


def test_pb2_requires_bounds():
    from ray_tpu.tune import PB2

    with pytest.raises(ValueError, match="hyperparam_bounds"):
        PB2(metric="score", mode="max")
    with pytest.raises(ValueError, match="bad bounds"):
        PB2(metric="score", mode="max",
            hyperparam_bounds={"lr": (1.0, 1.0)})


def test_pb2_gp_selects_near_optimum():
    """Feed the GP synthetic reward-change data peaked at lr=0.5: the
    UCB argmax should land near 0.5 far more often than uniform-random
    would (which averages |lr-0.5| = 0.25)."""
    from ray_tpu.tune import PB2

    pb2 = PB2(metric="score", mode="max",
              hyperparam_bounds={"lr": (0.0, 1.0)},
              perturbation_interval=1, seed=0)
    rng = np.random.default_rng(0)
    # 8 fake trials at random lrs reporting scores whose per-step
    # improvement is highest at lr=0.5.
    trials = [FakeTrial(f"t{i}", {"lr": float(rng.random())})
              for i in range(8)]
    scores = {t.trial_id: 0.0 for t in trials}
    for step in range(1, 6):
        for t in trials:
            rate = 1.0 - abs(t.config["lr"] - 0.5) * 2  # peak at 0.5
            scores[t.trial_id] += rate
            pb2.on_result(t, {"score": scores[t.trial_id],
                              "training_iteration": step})
    picks = [pb2.mutate_config({"lr": 0.9})["lr"] for _ in range(16)]
    assert all(0.0 <= p <= 1.0 for p in picks)
    mean_err = float(np.mean([abs(p - 0.5) for p in picks]))
    assert mean_err < 0.2, f"GP picks not concentrated: {picks}"


def test_pb2_cold_start_random_in_bounds():
    from ray_tpu.tune import PB2

    pb2 = PB2(metric="score", mode="max",
              hyperparam_bounds={"lr": (1e-5, 1e-1)},
              log_scale_keys=("lr",), seed=3)
    out = pb2.mutate_config({"lr": 1e-3})
    assert 1e-5 <= out["lr"] <= 1e-1


def test_pb2_end_to_end_tuner(rt_shared):
    """PB2 drives a population toward the high-improvement region."""
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.session import get_checkpoint
    from ray_tpu.tune import PB2, TuneConfig, Tuner, grid_search, report

    def objective(config):
        ck = get_checkpoint()
        level = ck.to_dict()["level"] if ck else 0.0
        for _ in range(15):
            # Improvement rate peaks at lr = 0.6.
            level += max(0.0, 1.0 - abs(config["lr"] - 0.6) * 3)
            report({"score": level},
                   checkpoint=Checkpoint.from_dict({"level": level}))
            time.sleep(0.01)

    scheduler = PB2(metric="score", mode="max", perturbation_interval=3,
                    hyperparam_bounds={"lr": (0.0, 1.0)}, seed=1)
    results = Tuner(
        objective,
        param_space={"lr": grid_search([0.05, 0.9, 0.55])},
        tune_config=TuneConfig(scheduler=scheduler,
                               max_concurrent_trials=3),
    ).fit()
    best = results.get_best_result("score", mode="max")
    assert best.last_result["score"] > 10


def test_bohb_model_uses_largest_adequate_budget():
    from ray_tpu.tune import BOHBSearcher, uniform

    s = BOHBSearcher({"x": uniform(0, 1)}, metric="loss", mode="min",
                     min_points_in_model=3, seed=0)
    # Low-budget observations fill first.
    for i in range(4):
        tid = f"a{i}"
        s._live[tid] = {"x": 0.1 * i}
        s.on_trial_complete(tid, {"loss": 1.0, "training_iteration": 1})
    assert len(s._history) == 4  # budget 1 qualified
    # Higher budget with enough points takes over.
    for i in range(3):
        tid = f"b{i}"
        s._live[tid] = {"x": 0.5 + 0.1 * i}
        s.on_trial_complete(tid, {"loss": 0.5, "training_iteration": 9})
    assert len(s._history) == 3
    assert all(cfg["x"] >= 0.5 for cfg, _ in s._history)


def test_bohb_end_to_end(rt_shared):
    """create_bohb pair: ASHA prunes, the KDE model concentrates near
    the optimum; the sweep finds x near 0.7."""
    from ray_tpu.tune import TuneConfig, Tuner, create_bohb, report, uniform

    def objective(config):
        for i in range(9):
            # Converges toward the true objective value over budget.
            frac = (i + 1) / 9
            report({"loss": frac * (config["x"] - 0.7) ** 2
                    + (1 - frac) * 0.5})
            # Stream reports (a zero-latency loop finishes before the
            # runner polls, so ASHA could never prune mid-flight).
            time.sleep(0.03)

    scheduler, searcher = create_bohb(
        {"x": uniform(0, 1)}, metric="loss", mode="min", max_t=9,
        grace_period=3, max_trials=24, seed=0)
    results = Tuner(
        objective, param_space=None,
        tune_config=TuneConfig(scheduler=scheduler, search_alg=searcher,
                               max_concurrent_trials=2),
    ).fit()
    best = results.get_best_result("loss", mode="min")
    assert abs(best.config["x"] - 0.7) < 0.2
    # ASHA actually pruned something (not every trial ran to max_t).
    iters = [t.last_result.get("training_iteration", 0)
             for t in results.trials if t.last_result]
    assert min(iters) < 9
