import time

def test_async_dbg(rt_init):
    rt = rt_init

    t_def = time.monotonic()

    @rt.remote
    class AsyncGather:
        def __init__(self):
            self.t_init = time.monotonic()
        async def ping(self):
            return time.monotonic()

    @rt.remote
    class SyncActor:
        def __init__(self):
            pass
        def ping(self):
            return time.monotonic()

    s = SyncActor.remote()
    print("sync create+ping", rt.get(s.ping.remote(), timeout=60) - t_def)

    t1 = time.monotonic()
    a = AsyncGather.remote()
    print("async ping", rt.get(a.ping.remote(), timeout=60) - t1)
    t2 = time.monotonic()
    print("async ping2", rt.get(a.ping.remote(), timeout=60) - t2)
