"""Core API tests: tasks, objects, put/get/wait.

Mirrors the reference's ``python/ray/tests/test_basic.py`` coverage.
"""

import time

import numpy as np
import pytest


def test_put_get(rt_shared):
    rt = rt_shared
    ref = rt.put(42)
    assert rt.get(ref) == 42


def test_put_get_large_numpy(rt_shared):
    rt = rt_shared
    arr = np.arange(1_000_000, dtype=np.float32)  # ~4MB -> shm path
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(rt_shared):
    rt = rt_shared

    @rt.remote
    def f(x):
        return x * 2

    assert rt.get(f.remote(2)) == 4


def test_task_with_ref_arg(rt_shared):
    rt = rt_shared

    @rt.remote
    def add(a, b):
        return a + b

    x = rt.put(1)
    y = add.remote(x, 2)
    z = add.remote(y, 4)
    assert rt.get(z) == 7


def test_many_tasks(rt_shared):
    rt = rt_shared

    @rt.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert rt.get(refs) == [i * i for i in range(50)]


def test_multiple_returns(rt_shared):
    rt = rt_shared

    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(rt_shared):
    rt = rt_shared

    @rt.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(Exception) as exc_info:
        rt.get(boom.remote())
    assert "kapow" in str(exc_info.value)


def test_error_cascades_through_deps(rt_shared):
    rt = rt_shared

    @rt.remote
    def boom():
        raise ValueError("root cause")

    @rt.remote
    def consume(x):
        return x

    with pytest.raises(Exception) as exc_info:
        rt.get(consume.remote(boom.remote()))
    assert "root cause" in str(exc_info.value)


def test_wait(rt_shared):
    rt = rt_shared

    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(2)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = rt.wait([f, s], num_returns=1, timeout=1.5)
    assert ready == [f]
    assert not_ready == [s]


def test_get_timeout(rt_shared):
    rt = rt_shared

    @rt.remote
    def sleepy():
        time.sleep(5)

    with pytest.raises(rt.GetTimeoutError):
        rt.get(sleepy.remote(), timeout=0.2)


def test_nested_tasks(rt_shared):
    rt = rt_shared

    @rt.remote
    def inner(x):
        return x + 1

    @rt.remote
    def outer(x):
        import ray_tpu as rt2

        return rt2.get(inner.remote(x)) + 10

    assert rt.get(outer.remote(1)) == 12


def test_nested_refs_pass_through(rt_shared):
    rt = rt_shared

    @rt.remote
    def make():
        return 7

    @rt.remote
    def takes_list(refs):
        import ray_tpu as rt2

        return sum(rt2.get(refs))

    refs = [make.remote() for _ in range(3)]
    assert rt.get(takes_list.remote(refs)) == 21


def test_put_inside_task(rt_shared):
    rt = rt_shared

    @rt.remote
    def producer():
        import ray_tpu as rt2

        return rt2.put([1, 2, 3])

    inner_ref = rt.get(producer.remote())
    assert rt.get(inner_ref) == [1, 2, 3]


def test_options_override(rt_shared):
    rt = rt_shared

    @rt.remote(num_cpus=1)
    def f():
        return "ok"

    assert rt.get(f.options(num_cpus=2).remote()) == "ok"


def test_cluster_resources(rt_shared):
    rt = rt_shared
    res = rt.cluster_resources()
    assert res.get("CPU", 0) >= 4


def test_worker_wait_num_returns_validation(rt_init):
    """A worker-side wait with num_returns > len(refs) must error, not
    hang (regression: the async wait RPC dropped the validation)."""
    import ray_tpu as rt

    @rt.remote
    def inner():
        return 1

    @rt.remote
    def waiter():
        ref = inner.remote()
        try:
            rt.wait([ref], num_returns=2, timeout=5)
            return "no-error"
        except ValueError:
            return "value-error"

    assert rt.get(waiter.remote(), timeout=30) == "value-error"


def test_worker_wait_timeout_returns_partial(rt_init):
    """wait() from a worker with a timeout returns the ready subset."""
    import time as _time

    import ray_tpu as rt

    @rt.remote
    def fast():
        return "f"

    @rt.remote
    def slow():
        _time.sleep(8)
        return "s"

    @rt.remote
    def waiter():
        refs = [fast.remote(), slow.remote()]
        ready, not_ready = rt.wait(refs, num_returns=2, timeout=1.5)
        return len(ready), len(not_ready)

    n_ready, n_not = rt.get(waiter.remote(), timeout=30)
    assert n_ready == 1 and n_not == 1


def test_get_large_numpy_zero_copy(rt_shared):
    """The get path must not copy the payload: repeated gets of the same
    object return read-only numpy views aliasing ONE shm extent
    (reference: plasma zero-copy numpy out of shm, BASELINE '100 GiB+
    ray.get'). Write path is likewise out-of-band straight into the
    arena (``SerializedObject.write_into``)."""
    import numpy as np

    rt = rt_shared
    arr = np.arange(1_000_000, dtype=np.float32)  # 4MB >> inline limit
    ref = rt.put(arr)
    a = rt.get(ref)
    b = rt.get(ref)
    np.testing.assert_array_equal(a, arr)
    assert not a.flags.writeable  # sealed objects are immutable
    assert np.shares_memory(a, b), "two gets must alias one shm extent"
    # values stay valid after the ref (and thus the store entry) is gone:
    # the pin + deferred-free keep the extent alive until GC.
    del ref, b
    assert float(a.sum()) == float(arr.sum())
