"""Metrics inventory: every emitted ``rt_*`` series is documented.

Satellite of ISSUE 16: run a smoke workload that touches the task,
actor, serve-free LLM, and flight-recorder instrumentation, scrape the
dashboard's ``/metrics``, and assert every ``rt_*`` base name appearing
in the exposition is listed in COMPONENTS.md's "Metrics inventory"
table — so a new metric cannot ship undocumented (and a renamed one
cannot leave a stale table row pointing at nothing).
"""

import os
import re
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _documented_metrics() -> set:
    text = open(os.path.join(REPO, "COMPONENTS.md")).read()
    try:
        section = text.split("### Metrics inventory", 1)[1]
        section = section.split("\n## ", 1)[0]
    except IndexError:  # pragma: no cover - doc structure regression
        section = ""
    return set(re.findall(r"`(rt_[a-z0-9_]+)`", section))


def _emitted_base_names(text: str) -> set:
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if not name.startswith("rt_"):
            continue
        for suffix in _HIST_SUFFIXES:
            if name.endswith(suffix):
                name = name[:-len(suffix)]
                break
        names.add(name)
    return names


def test_every_emitted_metric_is_documented(rt_init):
    rt = rt_init

    @rt.remote
    def inv_task(x):
        return x + 1

    @rt.remote
    class InvActor:
        def ping(self):
            return 1

    assert rt.get([inv_task.remote(i) for i in range(4)]) == [1, 2, 3, 4]
    a = InvActor.remote()
    assert rt.get(a.ping.remote()) == 1
    # LLM family: a series only appears in the exposition once touched
    # — commit a zero roofline sample the way an idle engine would.
    from ray_tpu.llm.paged import llm_metrics

    m = llm_metrics()
    assert m is not None
    m["roofline_frac"].set(0.0)
    # One telemetry flush so worker-side series reach the head.
    from ray_tpu.core.config import config

    time.sleep(config().metrics_report_interval_ms / 1000.0 + 0.5)

    from ray_tpu.observability import start_dashboard, stop_dashboard

    start_dashboard(port=18277)
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:18277/metrics", timeout=15) as r:
            text = r.read().decode()
    finally:
        stop_dashboard()

    emitted = _emitted_base_names(text)
    documented = _documented_metrics()
    assert documented, "COMPONENTS.md metrics inventory table missing"
    # The workload above must actually exercise the planes under test.
    for required in ("rt_tasks_submitted", "rt_task_latency_seconds",
                     "rt_task_stage_seconds", "rt_llm_roofline_frac"):
        assert required in emitted, sorted(emitted)
    undocumented = emitted - documented
    assert not undocumented, (
        f"emitted metrics missing from COMPONENTS.md inventory: "
        f"{sorted(undocumented)}")
