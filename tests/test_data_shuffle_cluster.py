"""Push-based shuffle on a multi-node sim cluster, instrumented with
Dataset.stats() (VERDICT r3: the shuffle had no instrumentation to
prove it scales; reference: push_based_shuffle.py + _internal/stats).
"""

import time

import numpy as np
import pytest


@pytest.mark.slow
def test_push_shuffle_scales_on_sim_cluster(rt_cluster):
    import ray_tpu as rt
    from ray_tpu import data as rtd

    cluster = rt_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    n_rows = 40_000
    ds = rtd.from_items(list(range(n_rows)), parallelism=16)
    ds = ds.map_batches(lambda b: {"value": np.asarray(b["value"])})

    results = {}
    for tag, merge_factor in (("push_mf4", 4), ("wide_mf16", 16)):
        t0 = time.perf_counter()
        out = ds.random_shuffle(seed=5, merge_factor=merge_factor)
        count = out.count()
        wall = time.perf_counter() - t0
        assert count == n_rows
        stats = out.stats().summary()
        shuffle_stage = next(s for s in stats
                             if s["stage"].startswith("random_shuffle"))
        results[tag] = {"wall_s": round(wall, 2), "stage": shuffle_stage}
    # mf=16 >= blocks is the old single-round two-wave shuffle; mf=4 is
    # the pipelined push-based shape. Both must produce the full row
    # count across 4 nodes; report the instrumented comparison.
    print("shuffle comparison (4-node sim cluster, "
          f"{n_rows} rows, 16 blocks): {results}")
    assert "rounds=4," in results["push_mf4"]["stage"]["stage"]
    assert "rounds=1," in results["wide_mf16"]["stage"]["stage"]

    # Correctness at scale: the multiset of rows survives the shuffle.
    out = ds.random_shuffle(seed=7, merge_factor=4)
    total = 0
    checksum = 0
    for batch in out.iter_batches(batch_size=4096):
        v = np.asarray(batch["value"])
        total += v.size
        checksum += int(v.sum())
    assert total == n_rows
    assert checksum == n_rows * (n_rows - 1) // 2
