"""Parallel layer tests on the 8-device CPU mesh: collectives, ring
attention, Ulysses, pipeline, MoE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.attention import mha_reference
from ray_tpu.parallel import (
    MeshSpec,
    allgather,
    allreduce,
    broadcast,
    init_collective_group,
    moe_ffn_local,
    pipeline_apply,
    reducescatter,
    ring_attention,
    spec_for,
    ulysses_attention,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(scope="module")
def mesh8():
    return MeshSpec(dp=8).build()


def test_mesh_spec_axes():
    spec = MeshSpec.for_devices(8, tp=2, sp=2)
    assert spec.dp == 2 and spec.tp == 2 and spec.sp == 2
    mesh = spec.build()
    assert mesh.devices.size == 8
    assert spec.describe() == "dp=2xsp=2xtp=2"


def test_spec_for_rules():
    assert spec_for(("batch", "seq", "embed")) == P(("dp", "fsdp"), "sp", "fsdp")
    assert spec_for((None, "heads")) == P(None, "tp")


def test_allreduce(mesh8):
    init_collective_group(mesh8, axis="dp", group_name="t_ar")
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    out = allreduce(x, "sum", group_name="t_ar")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0))


def test_allgather_broadcast(mesh8):
    init_collective_group(mesh8, axis="dp", group_name="t_ag")
    x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)
    out = allgather(x, group_name="t_ag")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    b = broadcast(x, src_rank=2, group_name="t_ag")
    np.testing.assert_allclose(np.asarray(b), np.asarray(x)[2])


def test_reducescatter(mesh8):
    init_collective_group(mesh8, axis="dp", group_name="t_rs")
    x = jnp.ones((8, 8, 2), jnp.float32)
    out = reducescatter(x, "sum", group_name="t_rs")
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_ring_attention_matches_reference():
    mesh = MeshSpec(sp=8).build()
    B, H, S, D = 2, 4, 128, 16
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D), jnp.float32)
        for i in range(3)
    )
    ref = mha_reference(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, axis_name="sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_non_causal():
    mesh = MeshSpec(sp=4, dp=2).build()
    B, H, S, D = 2, 2, 64, 8
    key = jax.random.PRNGKey(2)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D), jnp.float32)
        for i in range(3)
    )
    ref = mha_reference(q, k, v, causal=False)
    out = ring_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_flow():
    mesh = MeshSpec(sp=8).build()
    B, H, S, D = 1, 2, 64, 8
    key = jax.random.PRNGKey(3)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D), jnp.float32)
        for i in range(3)
    )

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v) ** 2).sum()

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_ulysses_matches_reference():
    mesh = MeshSpec(sp=8).build()
    B, H, S, D = 2, 8, 128, 16  # heads divisible by sp
    key = jax.random.PRNGKey(4)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D), jnp.float32)
        for i in range(3)
    )
    ref = mha_reference(q, k, v, causal=True)
    out = ulysses_attention(q, k, v, mesh, axis_name="sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pipeline_matches_sequential():
    mesh = MeshSpec(pp=4).build(jax.devices()[:4])
    n_stage, micro, mb, dim = 4, 8, 4, 16
    key = jax.random.PRNGKey(5)
    ws = jax.random.normal(key, (n_stage, dim, dim), jnp.float32) * 0.3

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    x = jax.random.normal(jax.random.fold_in(key, 9), (micro, mb, dim))
    # Sequential reference: apply stages in order.
    ref = x
    for i in range(n_stage):
        ref = stage_fn(ws[i], ref)

    out = pipeline_apply(stage_fn, ws, x, mesh, axis_name="pp",
                         params_spec=P("pp"), data_spec=P())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_moe_local_no_ep():
    tokens, model, hidden, E = 64, 16, 32, 4
    key = jax.random.PRNGKey(6)
    x = jax.random.normal(key, (tokens, model))
    router_w = jax.random.normal(jax.random.fold_in(key, 1), (model, E)) * 0.1
    w_in = jax.random.normal(jax.random.fold_in(key, 2), (E, model, hidden)) * 0.1
    w_out = jax.random.normal(jax.random.fold_in(key, 3), (E, hidden, model)) * 0.1
    y, aux = moe_ffn_local(x, router_w, w_in, w_out, num_experts=E,
                           top_k=2, axis_name=None, capacity_factor=2.0)
    assert y.shape == x.shape
    assert float(aux) > 0
    assert not np.isnan(np.asarray(y)).any()


def test_moe_expert_parallel():
    from functools import partial

    from jax.experimental.shard_map import shard_map

    mesh = MeshSpec(ep=4).build(jax.devices()[:4])
    tokens, model, hidden, E = 32, 8, 16, 4
    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (4 * tokens, model))
    router_w = jax.random.normal(jax.random.fold_in(key, 1), (model, E)) * 0.1
    w_in = jax.random.normal(jax.random.fold_in(key, 2), (E, model, hidden)) * 0.1
    w_out = jax.random.normal(jax.random.fold_in(key, 3), (E, hidden, model)) * 0.1

    fn = shard_map(
        partial(moe_ffn_local, num_experts=E, top_k=1, axis_name="ep",
                capacity_factor=4.0),
        mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=(P("ep"), P()),
        check_rep=False,
    )
    y, aux = fn(x, router_w, w_in, w_out)
    assert y.shape == x.shape
    assert not np.isnan(np.asarray(y)).any()


def test_moe_expert_parallel_matches_local():
    """EP-sharded MoE must be numerically IDENTICAL to running each token
    shard through the local (no-ep) path — regression for the all_to_all
    slot-ordering bug that e_local=1 tests couldn't see (untiled a2a
    removes the split axis and inserts the device axis at concat)."""
    from functools import partial

    from jax.experimental.shard_map import shard_map

    mesh = MeshSpec(ep=4).build(jax.devices()[:4])
    tokens, model, hidden, E = 32, 8, 16, 8  # e_local = 2
    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (4 * tokens, model))
    router_w = jax.random.normal(jax.random.fold_in(key, 1), (model, E)) * 0.1
    w_in = jax.random.normal(
        jax.random.fold_in(key, 2), (E, model, hidden)) * 0.1
    w_out = jax.random.normal(
        jax.random.fold_in(key, 3), (E, hidden, model)) * 0.1

    fn = shard_map(
        partial(moe_ffn_local, num_experts=E, top_k=1, axis_name="ep",
                capacity_factor=8.0),
        mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=(P("ep"), P()),
        check_rep=False,
    )
    y, _ = fn(x, router_w, w_in, w_out)
    ref = jnp.concatenate([
        moe_ffn_local(x[i * tokens:(i + 1) * tokens], router_w, w_in,
                      w_out, num_experts=E, top_k=1, axis_name=None,
                      capacity_factor=8.0)[0]
        for i in range(4)
    ], axis=0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_moe_gpt_ep_train_step_decreases_loss():
    """MoE-GPT (num_experts>0) trains over an ep mesh through
    build_sharded_train: finite decreasing loss, nonzero grads."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel.sharding import prune_rules_for_mesh
    from ray_tpu.train.step import build_sharded_train

    cfg = gpt2.GPT2Config(
        vocab_size=256, max_seq=32, num_layers=2, num_heads=4, d_model=64,
        dtype=jnp.float32, attention_impl="reference", remat=False,
        num_experts=8, moe_top_k=2,
    )
    mesh = MeshSpec(dp=2, ep=4).build(jax.devices()[:8])
    over = {"batch": ("dp", "fsdp", "ep")}
    rules = prune_rules_for_mesh(mesh, over)
    sinit, sstep, _ = build_sharded_train(
        lambda key: gpt2.init_params(key, cfg),
        lambda p, b: gpt2.loss_fn(p, b, cfg, rules=rules),
        mesh, rules=over, master_fp32=False,
    )
    params, opt_state, step = sinit(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
    losses = []
    for _ in range(4):
        params, opt_state, step, m = sstep(params, opt_state, step,
                                           {"tokens": tokens})
        losses.append(float(m["loss"]))
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
    assert float(m["grad_norm"]) > 0


def test_gpt_pp_pipeline_train_step_decreases_loss():
    """GPT with blocks pipelined over pp ({"layers": "pp"} rules) trains
    through build_sharded_train: finite decreasing loss."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel.sharding import prune_rules_for_mesh
    from ray_tpu.train.step import build_sharded_train

    cfg = gpt2.GPT2Config(
        vocab_size=256, max_seq=32, num_layers=4, num_heads=4, d_model=64,
        dtype=jnp.float32, attention_impl="reference", remat=False,
    )
    mesh = MeshSpec(dp=2, pp=4).build(jax.devices()[:8])
    over = {"layers": "pp"}
    rules = prune_rules_for_mesh(mesh, over)
    sinit, sstep, _ = build_sharded_train(
        lambda key: gpt2.init_params(key, cfg),
        lambda p, b: gpt2.loss_fn(p, b, cfg, rules=rules),
        mesh, rules=over, master_fp32=False,
    )
    params, opt_state, step = sinit(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 33), 0, 256)
    losses = []
    for _ in range(4):
        params, opt_state, step, m = sstep(params, opt_state, step,
                                           {"tokens": tokens})
        losses.append(float(m["loss"]))
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
    assert float(m["grad_norm"]) > 0
