"""Ring-flash parity: the flash-block ring body must match full
attention and the einsum ring body (VERDICT r4 item 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import mha_reference, mha_reference_with_lse
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.parallel.ring import ring_attention


def _qkv(b=2, h=4, s=256, d=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_full_reference(causal):
    q, k, v = _qkv()
    mesh = MeshSpec(sp=4).build(jax.devices()[:4])
    out = ring_attention(q, k, v, mesh, causal=causal, batch_axes=(),
                         heads_axis=None, impl="flash")
    ref = mha_reference(q, k, v, causal=causal)
    err = float(jnp.max(jnp.abs(out - ref)))
    assert err < 1e-2, f"ring-flash vs reference max err {err}"
    assert err < 1e-4  # fp32 blocks should be much tighter than 1e-2


def test_ring_flash_matches_einsum_ring():
    q, k, v = _qkv(seed=3)
    mesh = MeshSpec(sp=4).build(jax.devices()[:4])
    flash = ring_attention(q, k, v, mesh, causal=True, batch_axes=(),
                           heads_axis=None, impl="flash")
    einsum = ring_attention(q, k, v, mesh, causal=True, batch_axes=(),
                            heads_axis=None, impl="einsum")
    np.testing.assert_allclose(np.asarray(flash), np.asarray(einsum),
                               atol=1e-4, rtol=1e-4)


def test_ring_flash_pallas_kernel_interpret():
    """Exercise the REAL pallas lse-producing kernel (interpret mode on
    CPU) inside the ring merge — impl='auto' would silently fall back
    to the reference path off-TPU and leave the kernel's lse contract
    uncovered."""
    import functools

    from ray_tpu.parallel.ring import ring_flash_attention_local
    from ray_tpu.parallel.sharding import smap
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(b=1, h=2, s=128, d=32, seed=5)
    mesh = MeshSpec(sp=2).build(jax.devices()[:2])
    spec = P(None, None, "sp", None)
    fn = smap(
        functools.partial(ring_flash_attention_local, axis_name="sp",
                          causal=True, block_impl="flash"),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)))
    assert err < 1e-2, f"pallas-block ring vs reference max err {err}"


def test_reference_with_lse_consistent():
    q, k, v = _qkv(b=1, h=2, s=64, d=16, seed=7)
    o, lse = mha_reference_with_lse(q, k, v, causal=True)
    o2 = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o2), atol=1e-5)
    # lse really is logsumexp of the scaled causal logits
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("bhqd,bhkd->bhqk", np.asarray(q),
                       np.asarray(k)).astype(np.float64) * scale
    s = q.shape[2]
    mask = np.arange(s)[:, None] >= np.arange(s)[None, :]
    logits = np.where(mask, logits, -1e30)
    want = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)
                  ) + logits.max(-1)
    np.testing.assert_allclose(np.asarray(lse), want, atol=1e-3)
