"""Tune Syncer: experiment state mirrors to a storage URI and restores
onto a fresh workdir with the ORIGINAL local staging dir deleted
(VERDICT r4 item 8; reference tune/syncer.py:184,209,231)."""

import os
import shutil

import pytest

from ray_tpu.core.storage import client_for_uri
from ray_tpu.tune.syncer import Syncer, is_uri


def test_syncer_roundtrip(tmp_path):
    local = tmp_path / "local"
    (local / "sub").mkdir(parents=True)
    (local / "a.txt").write_bytes(b"alpha")
    (local / "sub" / "b.bin").write_bytes(b"\x00\x01")
    (local / "junk.tmp").write_bytes(b"skip me")
    uri = f"file://{tmp_path}/remote/exp"
    s = Syncer(uri)
    assert s.sync_up(str(local)) == 2  # .tmp excluded
    down = tmp_path / "down"
    assert Syncer(uri).sync_down(str(down)) == 2
    assert (down / "a.txt").read_bytes() == b"alpha"
    assert (down / "sub" / "b.bin").read_bytes() == b"\x00\x01"
    assert not (down / "junk.tmp").exists()


def test_is_uri():
    assert is_uri("file:///x/y")
    assert is_uri("mock://bucket/k")
    assert not is_uri("/plain/path")
    assert not is_uri(None)


def test_tuner_syncs_and_restores_from_uri(tmp_path, rt_shared):
    """End-to-end: sweep uploads to a URI; the local staging dir is
    DELETED; Tuner.restore(uri) resumes and finishes the budget."""
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    uri_root = f"file://{tmp_path}/bucket"

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=TuneConfig(max_concurrent_trials=2),
        run_config=RunConfig(name="sync-exp", storage_path=uri_root),
    )
    grid = tuner.fit()
    assert len(grid.trials) == 4
    assert all(t.status == "TERMINATED" for t in grid.trials)

    # the remote mirror holds the experiment state
    exp_uri = uri_root + "/sync-exp"
    assert Tuner.can_restore(exp_uri)
    client = client_for_uri(exp_uri)
    assert client.exists("experiment_state.pkl")

    # destroy the local staging dir entirely (uniqued per Tuner)
    staging = tuner._experiment_path()
    assert "rt_tune_staging" in staging and os.path.isdir(staging)
    shutil.rmtree(staging)

    restored = Tuner.restore(exp_uri)
    grid2 = restored.fit()
    assert len(grid2.trials) == 4
    # completed trials kept their results without retraining
    best = grid2.get_best_result(metric="score", mode="max")
    assert best.last_result["score"] == 12  # x=4, 3 reports
    assert not Tuner.can_restore(uri_root + "/absent")
