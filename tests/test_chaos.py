"""Chaos tests: workloads survive repeated random node loss.

Reference coverage analog: release/nightly_tests/chaos_test/ — a
NodeKiller removes nodes mid-workload; tasks with retries and
lineage-recoverable objects must still complete.
"""

import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos


def test_tasks_survive_node_killer(rt_cluster):
    import ray_tpu as rt
    from ray_tpu.cluster_utils import NodeKiller

    cluster = rt_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @rt.remote(max_retries=5)
    def work(i):
        time.sleep(0.3)
        return i * 3

    killer = NodeKiller(cluster, kill_interval_s=0.25, max_kills=2)
    refs = [work.remote(i) for i in range(40)]
    time.sleep(0.2)  # let tasks spread across nodes first
    killer.run()
    try:
        results = rt.get(refs, timeout=120)
    finally:
        killer.stop()
    assert results == [i * 3 for i in range(40)]
    assert len(killer.killed) >= 1, "chaos must actually kill nodes"


def test_lineage_survives_explicit_kill(rt_cluster):
    import ray_tpu as rt
    from ray_tpu.cluster_utils import NodeKiller

    cluster = rt_cluster
    node = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @rt.remote(max_retries=3)
    def produce():
        return np.arange(1000)

    ref = produce.remote()
    rt.wait([ref], timeout=10)
    killer = NodeKiller(cluster)
    killer.kill_one()
    # Object may have lived on the killed node: lineage reconstruction
    # must transparently recompute it.
    out = rt.get(ref, timeout=30)
    np.testing.assert_array_equal(out, np.arange(1000))


def test_killer_never_kills_head(rt_cluster):
    from ray_tpu.cluster_utils import NodeKiller

    cluster = rt_cluster  # head only
    killer = NodeKiller(cluster)
    assert killer.kill_one() is None
    assert cluster.head_node_id in cluster._nodes
