"""Pipelined same-key tasks must not starve behind a blocked worker.

The scheduler eagerly fills a leased worker's pipe to PIPELINE_DEPTH with
same-key tasks. If the head-of-line task blocks indefinitely in get/wait
(e.g. on a gate actor), the queued tasks used to starve — even with idle
workers — because nothing could pull them back out of the pipe. The owner
now sends a "revoke" on worker-block; the worker returns the
never-started subset, which is rescheduled (reference analog: raylet
worker-lease cancellation, ``direct_task_transport.h`` OnWorkerIdle).
"""

import threading

import ray_tpu as rt


def test_blocked_worker_pipeline_no_starvation():
    rt.init(num_cpus=1, ignore_reinit_error=True)
    try:
        @rt.remote(max_concurrency=2)
        class Gate:
            def __init__(self):
                self.ev = threading.Event()

            def open(self):
                self.ev.set()
                return True

            def wait(self):
                self.ev.wait(60)
                return self.ev.is_set()

        gate = Gate.remote()

        @rt.remote
        def task(i, gate):
            if i == 0:
                # Blocks in rt.get inside the worker until the gate
                # opens — the head-of-line task of the pipelined lease.
                assert rt.get(gate.wait.remote())
                return -1
            return i

        refs = [task.remote(i, gate) for i in range(4)]
        # Tasks 1..3 must complete while task 0 is still blocked: the
        # revoke path reschedules them onto the worker the pool grew.
        done, pending = rt.wait(refs[1:], num_returns=3, timeout=30)
        assert len(done) == 3, (
            f"pipelined tasks starved behind blocked worker "
            f"({len(done)}/3 completed)")
        assert sorted(rt.get(done)) == [1, 2, 3]
        rt.get(gate.open.remote())
        assert rt.get(refs[0], timeout=30) == -1
    finally:
        rt.shutdown()
