"""Runtime-env plugin architecture tests
(reference: _private/runtime_env tests for plugin.py, uri_cache.py,
conda.py, container.py)."""

import os

import pytest

from ray_tpu.runtime_env import (
    RuntimeEnv,
    RuntimeEnvContext,
    RuntimeEnvPlugin,
    URICache,
    apply_runtime_env,
    register_plugin,
    restore_runtime_env,
    _PLUGINS,
)


def test_validation_routes_through_plugins(tmp_path):
    (tmp_path / "wd").mkdir()
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir=str(tmp_path / "wd"))
    assert env["env_vars"] == {"A": "1"}
    assert env["working_dir"] == str(tmp_path / "wd")
    with pytest.raises(TypeError, match="env_vars"):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError, match="unknown runtime_env fields"):
        RuntimeEnv(bogus_field=1)


def test_custom_plugin_full_lifecycle(tmp_path):
    calls = []

    class TokenPlugin(RuntimeEnvPlugin):
        name = "token"
        priority = 3

        def validate(self, value, env):
            if not isinstance(value, str):
                raise TypeError("token must be str")
            return value

        def get_uri(self, env):
            return f"token://{env['token']}"

        def create(self, uri, env):
            calls.append(("create", uri))
            return None, 1

        def modify_context(self, uri, env, ctx):
            calls.append(("modify", uri))
            ctx.env_vars["RT_TOKEN"] = env["token"]

    register_plugin(TokenPlugin())
    try:
        env = RuntimeEnv(token="sekrit")
        undo = apply_runtime_env(env)
        assert os.environ.get("RT_TOKEN") == "sekrit"
        restore_runtime_env(undo)
        assert os.environ.get("RT_TOKEN") is None
        # Second apply hits the URI cache: no second create.
        undo = apply_runtime_env(env)
        restore_runtime_env(undo)
        creates = [c for c in calls if c[0] == "create"]
        modifies = [c for c in calls if c[0] == "modify"]
        assert len(creates) == 1
        assert len(modifies) == 2
    finally:
        _PLUGINS.pop("token", None)


def test_plugin_priority_ordering(tmp_path):
    order = []

    class A(RuntimeEnvPlugin):
        name = "aaa"
        priority = 9

        def modify_context(self, uri, env, ctx):
            order.append("aaa")

    class B(RuntimeEnvPlugin):
        name = "bbb"
        priority = 2

        def modify_context(self, uri, env, ctx):
            order.append("bbb")

    register_plugin(A())
    register_plugin(B())
    try:
        undo = apply_runtime_env({"aaa": 1, "bbb": 1})
        restore_runtime_env(undo)
        assert order == ["bbb", "aaa"]
    finally:
        _PLUGINS.pop("aaa", None)
        _PLUGINS.pop("bbb", None)


def test_uri_cache_lru_eviction():
    deleted = []
    cache = URICache(max_total_bytes=100)
    cache.add("u1", 40, lambda u: deleted.append(u) or 40)
    cache.add("u2", 40, lambda u: deleted.append(u) or 40)
    assert cache.mark_used("u1")  # u1 now MRU
    cache.add("u3", 40, lambda u: deleted.append(u) or 40)
    # 120 > 100: evict LRU = u2 (u1 was refreshed).
    assert deleted == ["u2"]
    assert cache.mark_used("u1") and cache.mark_used("u3")
    assert not cache.mark_used("u2")


def test_sys_path_precedence_later_plugins_win(tmp_path):
    """pip site > py_modules > working_dir on sys.path: a pinned pip
    version must shadow a stale copy in the working dir."""
    import sys

    wd = tmp_path / "wd"
    wd.mkdir()
    pm = tmp_path / "mods"
    pm.mkdir()
    undo = apply_runtime_env({"working_dir": str(wd),
                              "py_modules": [str(pm)]})
    try:
        assert sys.path.index(str(pm)) < sys.path.index(str(wd))
    finally:
        restore_runtime_env(undo)


def test_uri_cache_pinned_entries_survive_eviction():
    deleted = []
    cache = URICache(max_total_bytes=100)
    cache.add("u1", 80, lambda u: deleted.append(u) or 80)
    cache.pin("u1")
    cache.add("u2", 80, lambda u: deleted.append(u) or 80)
    # Over budget, but u1 is pinned (in use): only unpinned entries go.
    assert "u1" not in deleted
    cache.unpin("u1")
    cache.add("u3", 80, lambda u: deleted.append(u) or 80)
    assert "u1" in deleted


def test_uri_cache_new_entry_pinned_before_add_survives():
    """A freshly materialized resource is pinned before add(): its own
    add-triggered eviction pass must not delete it, even when every
    other entry is pinned too."""
    deleted = []
    cache = URICache(max_total_bytes=100)
    cache.add("old", 90, lambda u: deleted.append(u) or 90)
    cache.pin("old")
    cache.pin("new")
    cache.add("new", 50, lambda u: deleted.append(u) or 50)
    assert deleted == []  # over budget but everything is in use
    cache.unpin("old")
    cache.add("other", 10, lambda u: deleted.append(u) or 10)
    assert deleted == ["old"]


def test_apply_failure_releases_pins(tmp_path, monkeypatch):
    """A later plugin raising mid-apply must unpin earlier plugins'
    URIs (otherwise retries leak pins forever)."""
    from ray_tpu.runtime_env import _URI_CACHE

    class GoodPlugin(RuntimeEnvPlugin):
        name = "goodres"
        priority = 3

        def get_uri(self, env):
            return "goodres://x"

        def create(self, uri, env):
            return None, 1

    class BadPlugin(RuntimeEnvPlugin):
        name = "badres"
        priority = 8

        def create(self, uri, env):
            raise RuntimeError("boom")

    register_plugin(GoodPlugin())
    register_plugin(BadPlugin())
    try:
        with pytest.raises(RuntimeError, match="boom"):
            apply_runtime_env({"goodres": 1, "badres": 1})
        assert "goodres://x" not in _URI_CACHE._pins
    finally:
        _PLUGINS.pop("goodres", None)
        _PLUGINS.pop("badres", None)


def test_conda_gating():
    env = RuntimeEnv(conda="some-env-that-is-not-active")
    with pytest.raises(RuntimeError, match="offline"):
        apply_runtime_env(env)
    with pytest.raises(ValueError, match="dependencies"):
        RuntimeEnv(conda={"name": "x"})
    # Naming the active env (if any) is a no-op pass-through.
    active = os.environ.get("CONDA_DEFAULT_ENV")
    if active:
        restore_runtime_env(apply_runtime_env(RuntimeEnv(conda=active)))


def test_container_gating():
    with pytest.raises(ValueError, match="image"):
        RuntimeEnv(container={"run_options": []})
    env = RuntimeEnv(container={"image": "repo/img:tag"})
    with pytest.raises(RuntimeError,
                       match="podman|docker|container runtime"):
        apply_runtime_env(env)


def test_env_var_plugin_loading(tmp_path, monkeypatch):
    mod = tmp_path / "my_plugmod.py"
    mod.write_text(
        "from ray_tpu.runtime_env import RuntimeEnvPlugin\n"
        "class MyPlugin(RuntimeEnvPlugin):\n"
        "    name = 'myext'\n"
        "    def modify_context(self, uri, env, ctx):\n"
        "        ctx.env_vars['MYEXT'] = str(env['myext'])\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("RT_RUNTIME_ENV_PLUGINS", "my_plugmod:MyPlugin")
    from ray_tpu.runtime_env import _load_env_plugins

    _load_env_plugins()
    try:
        undo = apply_runtime_env({"myext": 7})
        assert os.environ.get("MYEXT") == "7"
        restore_runtime_env(undo)
    finally:
        _PLUGINS.pop("myext", None)


def test_worker_applies_runtime_env_end_to_end(rt_shared, tmp_path):
    """The whole plugin chain runs inside a real worker process."""
    import ray_tpu as rt

    @rt.remote(runtime_env={"env_vars": {"RT_PLUGIN_E2E": "yes"}})
    def probe():
        return os.environ.get("RT_PLUGIN_E2E")

    assert rt.get(probe.remote()) == "yes"
