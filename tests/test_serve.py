"""Serve tests (mirrors ``python/ray/serve/tests`` coverage: deploy,
handles, scaling, HTTP, autoscaling policy math)."""

import json
import time
import urllib.request

import pytest


@pytest.fixture()
def serve_instance(rt_shared):
    from ray_tpu import serve

    serve.start(http_port=18123)
    yield serve
    serve.shutdown()


def test_function_deployment(serve_instance):
    serve = serve_instance

    @serve.deployment
    def echo(x=None):
        return {"echo": x}

    handle = serve.run(echo.bind())
    from ray_tpu.core import get

    assert get(handle.remote("hi"), timeout=30) == {"echo": "hi"}


def test_class_deployment_with_state(serve_instance):
    serve = serve_instance

    @serve.deployment
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, k=1):
            self.n += k
            return self.n

    handle = serve.run(Counter.bind(100))
    from ray_tpu.core import get

    assert get(handle.remote(), timeout=30) == 101
    assert get(handle.remote(10), timeout=30) == 111


def test_method_handle(serve_instance):
    serve = serve_instance

    @serve.deployment
    class Model:
        def predict(self, x):
            return x * 2

    handle = serve.run(Model.bind())
    from ray_tpu.core import get

    assert get(handle.predict.remote(21), timeout=30) == 42


def test_multiple_replicas(serve_instance):
    serve = serve_instance

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _=None):
            import os
            import time

            # Hold the slot long enough that in-flight load genuinely
            # accumulates past the router's slack during the burst —
            # instant returns would let completions race submissions
            # and keep every request on one replica.
            time.sleep(0.05)
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    from ray_tpu.core import get

    # Routing is sticky-with-slack: idle sequential traffic deliberately
    # stays on one hot replica, but CONCURRENT load beyond the slack
    # (Router._slack = 16) must spill across the set.
    refs = [handle.remote() for _ in range(30)]
    pids = set(get(refs, timeout=60))
    assert len(pids) >= 2  # load spreads across replicas

    deps = serve.list_deployments()
    assert deps["WhoAmI"]["num_replicas"] == 3


def test_redeploy_new_version(serve_instance):
    serve = serve_instance

    @serve.deployment
    def v(x=None):
        return "v1"

    handle = serve.run(v.bind())
    from ray_tpu.core import get

    assert get(handle.remote(), timeout=30) == "v1"

    @serve.deployment(name="v")
    def v2(x=None):
        return "v2"

    handle2 = serve.run(v2.bind())
    deadline = time.time() + 20
    while time.time() < deadline:
        if get(handle2.remote(), timeout=30) == "v2":
            break
        time.sleep(0.1)
    assert get(handle2.remote(), timeout=30) == "v2"


def test_http_proxy(serve_instance):
    serve = serve_instance

    @serve.deployment
    def api(payload=None):
        return {"got": payload}

    serve.run(api.bind())
    req = urllib.request.Request(
        "http://127.0.0.1:18123/api",
        data=json.dumps({"k": 1}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"k": 1}}


def test_http_unknown_deployment_404(serve_instance):
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen("http://127.0.0.1:18123/nope", timeout=30)
    assert e.value.code == 404


def test_batching(serve_instance):
    serve = serve_instance

    @serve.deployment(max_concurrent_queries=16)
    class Batcher:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def __call__(self, items):
            # items is the coalesced list of requests.
            return [{"batch_size": len(items), "item": it} for it in items]

    handle = serve.run(Batcher.bind())
    from ray_tpu.core import get

    refs = [handle.remote(i) for i in range(4)]
    out = get(refs, timeout=30)
    assert {o["item"] for o in out} == {0, 1, 2, 3}
    assert max(o["batch_size"] for o in out) >= 2  # coalesced


def test_autoscaling_policy_math():
    """Pure policy test (reference: test_autoscaling_policy.py style)."""
    from ray_tpu.serve._internal import AutoscalingConfig, ServeController

    c = ServeController()
    cfg = AutoscalingConfig(min_replicas=1, max_replicas=10,
                            target_num_ongoing_requests_per_replica=2,
                            upscale_delay_s=0.0, downscale_delay_s=0.0)
    from ray_tpu.serve._internal import DeploymentInfo

    info = DeploymentInfo(name="d", deployment_def=lambda: None,
                          autoscaling=cfg)
    c.deployments["d"] = info
    c.replicas["d"] = []
    # Monkeypatch ongoing metric.
    c._collect_ongoing = lambda name: 9.0
    assert c._autoscale_target("d", info) == 5  # ceil(9/2)
    c._collect_ongoing = lambda name: 0.0
    assert c._autoscale_target("d", info) == 1  # min_replicas
    c._collect_ongoing = lambda name: 1000.0
    assert c._autoscale_target("d", info) == 10  # max cap


def test_max_concurrent_queries_enforced(serve_instance):
    """A replica must never hold more than max_concurrent_queries
    concurrent requests under a burst (reference: router.py:62,221 —
    round 1's cap was decorative; now slots are released only when the
    RESULT completes)."""
    import threading
    import time as _time

    import ray_tpu as rt
    from ray_tpu import serve

    peak = {"value": 0}
    lock = threading.Lock()

    @serve.deployment(num_replicas=1, max_concurrent_queries=2)
    class Slow:
        def __init__(self):
            self.ongoing = 0
            self.peak = 0
            self.lock = threading.Lock()

        def __call__(self, x=None):
            with self.lock:
                self.ongoing += 1
                self.peak = max(self.peak, self.ongoing)
            _time.sleep(0.15)
            with self.lock:
                self.ongoing -= 1
            return "ok"

        def get_peak(self):
            return self.peak

    handle = serve.run(Slow.bind(), name="slowcap")
    # burst 10 requests from threads (assign blocks when slots are full)
    refs = []
    refs_lock = threading.Lock()

    def fire():
        r = handle.remote()
        with refs_lock:
            refs.append(r)

    threads = [threading.Thread(target=fire) for _ in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(refs) == 10
    assert all(v == "ok" for v in rt.get(refs, timeout=60))
    peak_seen = rt.get(handle.get_peak.remote(), timeout=30)
    assert peak_seen <= 2, f"replica saw {peak_seen} concurrent requests"


def test_serve_survives_handle_gc(serve_instance):
    """The detached controller keeps reconciling after driver-side
    handles are dropped (reference: detached ServeController actor)."""
    import gc

    import ray_tpu as rt
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    def Echo(x=None):
        return {"echo": x}

    handle = serve.run(Echo.bind(), name="gctest")
    assert rt.get(handle.remote("a"), timeout=30) == {"echo": "a"}
    del handle
    gc.collect()
    # a fresh handle resolved via the named controller still works
    handle2 = serve.get_deployment_handle("Echo")
    assert rt.get(handle2.remote("b"), timeout=30) == {"echo": "b"}
    assert "Echo" in serve.list_deployments()


def test_streaming_response_python_handle(serve_instance):
    """Generator deployments stream: chunks are pulled from the replica
    (``_Replica.next_chunks``) instead of materializing the whole body
    (reference: Serve streaming responses)."""
    serve = serve_instance

    @serve.deployment
    def streamer(n=5):
        def gen():
            for i in range(n):
                yield {"chunk": i}
        return gen()

    handle = serve.run(streamer.bind())
    out = list(handle.stream(4))
    assert out == [{"chunk": i} for i in range(4)]


def test_async_deployment_request_timeout(serve_instance):
    """request_timeout_s cancels slow coroutine handlers (reference:
    Serve request timeouts)."""
    serve = serve_instance

    @serve.deployment(request_timeout_s=0.3)
    async def slow(x=None):
        import asyncio

        await asyncio.sleep(5)
        return "never"

    handle = serve.run(slow.bind())
    from ray_tpu.core import get

    with pytest.raises(Exception, match="(?i)timeout|cancel"):
        get(handle.remote(), timeout=30)


def test_max_concurrent_queries_cap_under_burst(serve_instance):
    """Burst of requests >> cap: the replica must never observe more than
    max_concurrent_queries ongoing requests (router enforcement,
    reference: router.py:62,221)."""
    serve = serve_instance

    @serve.deployment(max_concurrent_queries=3)
    class Tracker:
        def __init__(self):
            self.peak = 0
            self.cur = 0

        async def __call__(self, x=None):
            import asyncio

            self.cur += 1
            self.peak = max(self.peak, self.cur)
            await asyncio.sleep(0.05)
            self.cur -= 1
            return self.peak

        async def peak_seen(self):
            return self.peak

    handle = serve.run(Tracker.bind())
    from ray_tpu.core import get

    refs = [handle.remote(i) for i in range(20)]
    get(refs, timeout=60)
    peak = get(handle.peak_seen.remote(), timeout=30)
    assert peak <= 3, f"cap violated: peak={peak}"
    assert peak >= 2, f"no concurrency at all: peak={peak}"
