"""Log monitor tests: worker stdout redirection -> tail -> driver echo.

Reference coverage analog: log monitor tests in python/ray/tests/ —
worker print() output reaches the driver with a worker prefix.
"""

import os
import time

import pytest


def test_log_monitor_tails_and_publishes(tmp_path):
    from ray_tpu.core.log_monitor import LogMonitor

    published = []
    mon = LogMonitor(str(tmp_path),
                     publish=lambda ch, msg: published.append((ch, msg)))
    log = tmp_path / "worker-abcd1234.out"
    log.write_text("line one\n")
    assert mon.poll_once() == 1
    log.write_text("line one\nline two\n")  # append
    assert mon.poll_once() == 1  # only the new line
    assert published[0][0] == "LOGS"
    assert published[0][1] == {"worker": "abcd1234", "stream": "out",
                               "line": "line one"}
    assert published[1][1]["line"] == "line two"


def test_log_monitor_err_stream(tmp_path):
    from ray_tpu.core.log_monitor import LogMonitor

    published = []
    mon = LogMonitor(str(tmp_path),
                     publish=lambda ch, msg: published.append(msg))
    (tmp_path / "worker-beef0000.err").write_text("oops\n")
    mon.poll_once()
    assert published == [{"worker": "beef0000", "stream": "err",
                          "line": "oops"}]


def test_worker_prints_reach_driver(rt_init, capfd):
    """End-to-end: a task's print() appears on the driver's stdout with
    the worker prefix (reference: '(worker pid=...) hello')."""
    rt = rt_init

    @rt.remote
    def chatty():
        print("hello from the worker")
        return 1

    assert rt.get(chatty.remote()) == 1
    deadline = time.monotonic() + 5
    seen = ""
    while time.monotonic() < deadline:
        seen += capfd.readouterr().out
        if "hello from the worker" in seen:
            break
        time.sleep(0.1)
    assert "hello from the worker" in seen
    assert "(worker=" in seen


def test_redirect_disabled_by_config(monkeypatch):
    monkeypatch.setenv("RT_WORKER_REDIRECT_LOGS", "0")
    from ray_tpu.core.config import Config

    Config.reset()
    import ray_tpu as rt

    rt.init(num_cpus=2)
    try:
        from ray_tpu.core.runtime import get_runtime

        assert get_runtime().session_log_dir is None
        assert get_runtime().log_monitor is None

        @rt.remote
        def f():
            return 2

        assert rt.get(f.remote()) == 2
    finally:
        rt.shutdown()
        Config.reset()
