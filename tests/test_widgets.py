"""Notebook HTML repr tests (reference: ray.widgets render tests)."""


def test_dataset_repr_html(rt_shared):
    from ray_tpu.data import from_items

    ds = from_items([{"a": i, "b": f"s{i}"} for i in range(10)],
                    parallelism=2)
    html = ds._repr_html_()
    assert "Dataset" in html and "<table>" in html
    assert "<b>a</b>" in html and "int" in html
    assert "s0" in html


def test_result_grid_repr_html(rt_shared):
    from ray_tpu.tune import Tuner, grid_search, report

    def obj(config):
        report({"score": config["x"] * 2.0})

    results = Tuner(obj, param_space={"x": grid_search([1, 2])}).fit()
    html = results._repr_html_()
    assert "<table>" in html and "TERMINATED" in html
    assert "score=2" in html or "score=4" in html
