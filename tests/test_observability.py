"""Observability tests: metrics, state API, events, dashboard HTTP."""

import json
import urllib.request

import pytest


def test_counter_gauge_histogram():
    from ray_tpu.observability import Counter, Gauge, Histogram, registry

    c = Counter("t_requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("t_depth")
    g.set(7)
    h = Histogram("t_latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    collected = registry.collect_all()
    assert collected["t_requests"][1][(("route", "/a"),)] == 3
    assert collected["t_depth"][1][()] == 7
    hist = collected["t_latency"][1][()]
    assert hist["count"] == 3
    assert hist["buckets"] == [1, 1, 1]

    text = registry.prometheus_text()
    assert 't_requests{route="/a"} 3' in text
    assert "t_latency_bucket" in text


def test_prometheus_exposition_strict():
    """Validate /metrics output against a strict line-format parser:
    sanitized metric/label names, escaped label values, numeric sample
    values, and the open histogram bucket labeled le="+Inf" (a bare
    ``inf`` is rejected by real prometheus scrapers)."""
    import re

    from ray_tpu.observability import Counter, Gauge, Histogram, registry

    c = Counter("strict.test-counter", tag_keys=("route",))
    c.inc(2, tags={"route": 'a"b\\c\nd'})  # needs escaping
    g = Gauge("strict gauge")  # space must sanitize
    g.set(1.5)
    h = Histogram("strict_hist", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)  # lands in the +Inf bucket

    text = registry.prometheus_text()
    type_line = re.compile(
        r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (?:counter|gauge|histogram)$")
    sample_line = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(?:\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
        r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
        r' [+-]?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
    for line in text.splitlines():
        if not line:
            continue
        assert type_line.match(line) or sample_line.match(line), \
            f"malformed exposition line: {line!r}"

    # Sanitization applied consistently (name rule == label-name rule).
    assert "strict_test_counter{" in text
    assert "strict_gauge 1.5" in text
    # Escaped label value round-trips on one line.
    assert 'route="a\\"b\\\\c\\nd"' in text
    # The open bucket is le="+Inf", equals the series count, and the
    # cumulative counts are monotonic.
    hist_lines = [ln for ln in text.splitlines()
                  if ln.startswith("strict_hist")]
    buckets = [float(ln.rsplit(" ", 1)[1]) for ln in hist_lines
               if "_bucket{" in ln]
    assert buckets == sorted(buckets) and buckets[-1] == 3
    inf_line = next(ln for ln in hist_lines if 'le="+Inf"' in ln)
    assert inf_line.rsplit(" ", 1)[1] == "3"
    count_line = next(ln for ln in hist_lines if "_count" in ln)
    assert count_line.rsplit(" ", 1)[1] == "3"
    assert not any(re.search(r'le="inf"', ln, re.IGNORECASE)
                   for ln in hist_lines)


def test_state_api(rt_shared):
    import ray_tpu as rt
    from ray_tpu.observability import (
        cluster_status,
        list_actors,
        list_nodes,
        list_tasks,
        list_workers,
        summarize_tasks,
    )

    @rt.remote
    def f():
        return 1

    rt.get([f.remote() for _ in range(3)])

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    rt.get(a.ping.remote())

    nodes = list_nodes()
    assert nodes and nodes[0]["alive"]
    tasks = list_tasks()
    assert any(t["name"] == "f" for t in tasks)
    actors = list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    workers = list_workers()
    assert any(w["state"] == "DEDICATED" for w in workers)
    assert summarize_tasks().get("DONE", 0) >= 3
    status = cluster_status()
    assert "Cluster status" in status and "CPU" in status


def test_events():
    from ray_tpu.observability import Severity, emit, global_event_log

    emit("test_label", "something happened", Severity.WARNING, detail=42)
    events = global_event_log().query(label="test_label")
    assert events
    assert events[-1]["severity"] == "WARNING"
    assert events[-1]["custom_fields"]["detail"] == 42


def test_dashboard_http(rt_shared):
    from ray_tpu.observability import start_dashboard, stop_dashboard

    start_dashboard(port=18266)
    try:
        with urllib.request.urlopen(
            "http://127.0.0.1:18266/healthz", timeout=10
        ) as r:
            assert r.read() == b"success"
        with urllib.request.urlopen(
            "http://127.0.0.1:18266/api/nodes", timeout=10
        ) as r:
            nodes = json.loads(r.read())
        assert nodes and "resources_total" in nodes[0]
        with urllib.request.urlopen(
            "http://127.0.0.1:18266/metrics", timeout=10
        ) as r:
            assert b"TYPE" in r.read()
    finally:
        stop_dashboard()


def test_dashboard_task_drilldown_and_logs(rt_shared):
    """Per-task detail + worker log tail over HTTP (reference: dashboard
    task pages + log proxying)."""
    import ray_tpu as rt
    from ray_tpu.observability import start_dashboard, stop_dashboard

    @rt.remote
    def noisy(x):
        print(f"working on {x}")
        return x + 1

    ref = noisy.remote(41)
    assert rt.get(ref, timeout=30) == 42
    start_dashboard(port=18267)
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:18267/api/tasks", timeout=10) as r:
            tasks = json.loads(r.read())
        target = next(t for t in tasks if t["name"] == "noisy")
        with urllib.request.urlopen(
                f"http://127.0.0.1:18267/api/task/{target['task_id']}",
                timeout=10) as r:
            detail = json.loads(r.read())
        assert detail["name"] == "noisy"
        assert detail["state"] == "DONE"
        assert detail["returns"] and detail["returns"][0]["status"]
        assert detail["max_retries"] >= 0
        # Unknown id answers an error payload, not a 500.
        with urllib.request.urlopen(
                "http://127.0.0.1:18267/api/task/" + "ab" * 10,
                timeout=10) as r:
            assert "error" in json.loads(r.read())
        with urllib.request.urlopen(
                "http://127.0.0.1:18267/api/workers", timeout=10) as r:
            workers = json.loads(r.read())
        assert workers
        found_print = False
        for w in workers:
            with urllib.request.urlopen(
                    "http://127.0.0.1:18267/api/logs/"
                    f"{w['worker_id']}?n=50", timeout=10) as r:
                logs = json.loads(r.read())
            if logs.get("out") and any("working on 41" in line
                                       for line in logs["out"]):
                found_print = True
        assert found_print, "task stdout not reachable over HTTP"
    finally:
        stop_dashboard()


def test_timeline_spans(tmp_path):
    from ray_tpu.observability import record_span, timeline

    record_span("task:f", "task", 1.0, 1.5, pid=1, tid=2)
    path = timeline(str(tmp_path / "tl.json"))
    data = json.load(open(path))
    assert any(e["name"] == "task:f" and e["dur"] == 500000.0 for e in data)


def test_dashboard_frontend_and_node_stats(rt_init):
    """The dashboard serves an HTML frontend at / and per-node hardware
    stats (reference: dashboard/client frontend + reporter agent)."""
    import json
    import urllib.request

    from ray_tpu.observability.dashboard import Dashboard

    dash = Dashboard(port=18341).start()
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:18341/", timeout=30) as resp:
            body = resp.read().decode()
        assert "<html" in body and "ray_tpu dashboard" in body
        with urllib.request.urlopen(
                "http://127.0.0.1:18341/api/node_stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats.get("mem_total_bytes", 0) > 0
        assert "loadavg_1m" in stats
    finally:
        dash.stop()
