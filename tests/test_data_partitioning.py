"""Partitioned layouts + metadata provider tests
(reference: python/ray/data/tests/test_partitioning.py)."""

import csv
import os

import pytest

from ray_tpu.data import (
    FastFileMetadataProvider,
    Partitioning,
    PartitionStyle,
    PathPartitionEncoder,
    PathPartitionFilter,
    PathPartitionParser,
    read_csv,
    write_partitioned,
    from_items,
    CSVDatasource,
    JSONDatasource,
)


def _write_csv(path, rows):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]))
        w.writeheader()
        for r in rows:
            w.writerow(r)


def _make_hive_tree(base):
    for year, month, vals in [(2023, 1, [1, 2]), (2023, 2, [3]),
                              (2024, 1, [4, 5, 6])]:
        _write_csv(
            os.path.join(base, f"year={year}", f"month={month}",
                         "data.csv"),
            [{"v": v} for v in vals])


def test_hive_parser_and_encoder(tmp_path):
    scheme = Partitioning(PartitionStyle.HIVE, str(tmp_path))
    parser = PathPartitionParser(scheme)
    p = str(tmp_path / "year=2024" / "month=07" / "f.csv")
    assert parser(p) == {"year": "2024", "month": "07"}
    enc = PathPartitionEncoder(
        Partitioning(PartitionStyle.HIVE, "", ("year", "month")))
    assert enc({"year": 2024, "month": 7}) == "year=2024/month=7"


def test_directory_parser_depth_checked(tmp_path):
    scheme = Partitioning(PartitionStyle.DIRECTORY, str(tmp_path),
                          ("year", "month"))
    parser = PathPartitionParser(scheme)
    assert parser(str(tmp_path / "2024" / "07" / "f.csv")) == \
        {"year": "2024", "month": "07"}
    with pytest.raises(ValueError, match="partition levels"):
        parser(str(tmp_path / "2024" / "f.csv"))  # too shallow
    with pytest.raises(ValueError, match="partition levels"):
        # Too deep: silently taking the last 2 levels would map wrong
        # segments to fields.
        parser(str(tmp_path / "2024" / "07" / "backfill" / "f.csv"))
    with pytest.raises(ValueError, match="field_names"):
        Partitioning(PartitionStyle.DIRECTORY, str(tmp_path))


def test_read_attaches_partition_columns(rt_shared, tmp_path):
    base = str(tmp_path / "tree")
    _make_hive_tree(base)
    ds = read_csv(base, partitioning=Partitioning(
        PartitionStyle.HIVE, base))
    rows = sorted(ds.take_all(), key=lambda r: r["v"])
    assert len(rows) == 6
    # Partition values arrive as typed columns.
    assert rows[0] == {"v": 1, "year": 2023, "month": 1}
    assert rows[5] == {"v": 6, "year": 2024, "month": 1}


def test_partition_filter_prunes_before_read(rt_shared, tmp_path):
    base = str(tmp_path / "tree")
    _make_hive_tree(base)
    flt = PathPartitionFilter.of(
        lambda d: d.get("year") == "2024", base_dir=base)
    ds = read_csv(base, partitioning=Partitioning(
        PartitionStyle.HIVE, base), partition_filter=flt)
    rows = ds.take_all()
    assert sorted(r["v"] for r in rows) == [4, 5, 6]
    assert all(r["year"] == 2024 for r in rows)


def test_fast_meta_provider_skips_stat(rt_shared, tmp_path):
    base = str(tmp_path / "tree")
    _make_hive_tree(base)
    ds = read_csv(base, meta_provider=FastFileMetadataProvider())
    assert len(ds.take_all()) == 6
    # No existence check for explicit paths:
    mp = FastFileMetadataProvider()
    assert mp.expand_paths("/definitely/missing.csv") == \
        ["/definitely/missing.csv"]
    assert mp.get_metadata("/definitely/missing.csv").size_bytes is None


def test_write_partitioned_round_trip(rt_shared, tmp_path):
    base = str(tmp_path / "out")
    ds = from_items([{"year": y, "month": m, "v": v}
                     for y, m, v in [(2023, 1, 10), (2023, 2, 20),
                                     (2024, 1, 30), (2024, 1, 31)]],
                    parallelism=2)
    paths = write_partitioned(ds, JSONDatasource(), base,
                              ["year", "month"])
    assert paths and all(p.endswith(".json") for p in paths)
    assert os.path.isdir(os.path.join(base, "year=2024", "month=1"))
    from ray_tpu.data import read_json

    back = read_json(base, partitioning=Partitioning(
        PartitionStyle.HIVE, base))
    rows = sorted(back.take_all(), key=lambda r: r["v"])
    assert [r["v"] for r in rows] == [10, 20, 30, 31]
    # Partition cols round-trip from the path, not the file body.
    assert rows[2] == {"v": 30, "year": 2024, "month": 1}


def test_partitioned_walk_skips_non_format_files(rt_shared, tmp_path):
    """_SUCCESS markers and READMEs in hive trees must not reach the
    format parser."""
    base = str(tmp_path / "tree")
    _make_hive_tree(base)
    open(os.path.join(base, "_SUCCESS"), "w").close()
    with open(os.path.join(base, "README.txt"), "w") as f:
        f.write("not a csv")
    ds = read_csv(base, partitioning=Partitioning(
        PartitionStyle.HIVE, base))
    assert len(ds.take_all()) == 6


def test_numpy_partitioned_read_gets_columns(rt_shared, tmp_path):
    import numpy as np
    from ray_tpu.data import read_numpy

    base = tmp_path / "np" / "split=train"
    base.mkdir(parents=True)
    np.save(base / "a.npy", np.arange(4))
    ds = read_numpy(str(tmp_path / "np"), partitioning=Partitioning(
        PartitionStyle.HIVE, str(tmp_path / "np")))
    rows = ds.take_all()
    assert len(rows) == 4
    assert all(r["split"] == "train" for r in rows)


def test_write_partitioned_requires_cols(rt_shared, tmp_path):
    ds = from_items([{"a": 1}])
    with pytest.raises(Exception, match="partition cols"):
        write_partitioned(ds, CSVDatasource(), str(tmp_path / "x"),
                          ["missing"])
