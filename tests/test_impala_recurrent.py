"""Recurrent V-trace for IMPALA/APPO (VERDICT r4 item 9): an LSTM
policy must learn a memory-dependent env where a feedforward policy
provably cannot beat chance."""

import numpy as np
import pytest

from ray_tpu.rllib.impala import ImpalaConfig

# RepeatPrevObs: reward 1 iff action == previous step's signal. Episode
# length 32, 3 signals -> feedforward ceiling ~ 1 + 31/3 ~= 11.3 per
# episode; one step of memory scores ~32.
CHANCE_CEILING = 16.0
MEMORY_FLOOR = 22.0


def _train(use_lstm: bool, iters: int):
    config = (
        ImpalaConfig()
        .environment("RepeatPrevObs")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                  rollout_fragment_length=32)
        .training(lr=8e-3, entropy_coeff=0.003, vf_coeff=0.5,
                  num_batches_per_iter=4)
        .debugging(seed=0)
    )
    config.model = {"use_lstm": use_lstm, "lstm_cell_size": 32,
                    "fcnet_hiddens": [32]}
    algo = config.build()
    best = -np.inf
    try:
        for _ in range(iters):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if use_lstm and best >= MEMORY_FLOOR:
                break
    finally:
        algo.stop()
    return best


def test_lstm_impala_learns_memory_env():
    best = _train(use_lstm=True, iters=120)
    assert best >= MEMORY_FLOOR, (
        f"LSTM IMPALA did not learn the memory env (best={best:.1f})")


def test_mlp_impala_stuck_at_chance():
    """The same budget for the MLP stays at the feedforward ceiling —
    proof the LSTM result comes from the recurrent pathway, not the
    env being trivially learnable."""
    best = _train(use_lstm=False, iters=40)
    assert best <= CHANCE_CEILING, (
        f"memory env is leaking state to the MLP (best={best:.1f})")


def test_appo_recurrent_smoke():
    from ray_tpu.rllib.appo import APPOConfig

    config = (
        APPOConfig()
        .environment("RepeatPrevObs")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                  rollout_fragment_length=16)
        .training(num_batches_per_iter=2)
        .debugging(seed=0)
    )
    config.model = {"use_lstm": True, "lstm_cell_size": 16,
                    "fcnet_hiddens": [32]}
    algo = config.build()
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert np.isfinite(r2["loss"])
        assert r2["num_learner_updates"] > r1["num_learner_updates"] - 1
    finally:
        algo.stop()
