"""Event-loop stats + dashboard drill-down tests
(reference: src/ray/common/asio/event_stats tests + dashboard modules)."""

import json
import time
import urllib.request

import ray_tpu as rt
from ray_tpu.observability.event_stats import EventStats, \
    global_event_stats


def test_event_stats_aggregation():
    es = EventStats()
    es.record("h1", 0.010)
    es.record("h1", 0.030)
    es.record("h2", 0.001)
    with es.measure("h3"):
        time.sleep(0.005)
    rows = es.snapshot()
    assert [r["handler"] for r in rows][0] == "h1"  # most total time
    h1 = rows[0]
    assert h1["count"] == 2
    assert abs(h1["total_ms"] - 40.0) < 1.0
    assert abs(h1["mean_us"] - 20_000) < 500
    assert abs(h1["max_ms"] - 30.0) < 1.0
    h3 = next(r for r in rows if r["handler"] == "h3")
    assert h3["count"] == 1 and h3["total_ms"] >= 4.0
    table = es.format_table()
    assert "h1" in table and "count" in table
    es.reset()
    assert es.snapshot() == []


def test_runtime_handlers_instrumented(rt_shared):
    """Task + actor traffic shows up in the global handler table."""

    @rt.remote
    def f(x):
        return x + 1

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    rt.get([f.remote(i) for i in range(10)])
    assert rt.get(a.ping.remote()) == "pong"
    rows = global_event_stats().snapshot()
    names = {r["handler"] for r in rows}
    assert any(n.startswith("runtime.worker_msg.") for n in names), names
    from ray_tpu.observability import event_loop_stats

    api_rows = event_loop_stats(top=5)
    assert len(api_rows) <= 5
    assert api_rows == sorted(api_rows, key=lambda r: -r["total_ms"])


def test_dashboard_new_routes(rt_shared):
    from ray_tpu.observability.dashboard import Dashboard

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    rt.get(c.inc.remote())

    dash = Dashboard(port=18377).start()
    try:
        base = "http://127.0.0.1:18377"
        with urllib.request.urlopen(f"{base}/api/event_stats") as r:
            stats = json.loads(r.read())
        assert isinstance(stats, list) and stats
        with urllib.request.urlopen(f"{base}/api/jobs") as r:
            json.loads(r.read())
        with urllib.request.urlopen(f"{base}/api/actors") as r:
            actors = json.loads(r.read())
        assert actors
        aid = actors[-1]["actor_id"]
        with urllib.request.urlopen(f"{base}/api/actor/{aid}") as r:
            detail = json.loads(r.read())
        assert detail["actor_id"] == aid
        assert detail["state"] in ("ALIVE", "RUNNING", "STARTED")
        with urllib.request.urlopen(base + "/") as r:
            html = r.read().decode()
        assert "event_stats" in html and "overview" in html
    finally:
        dash.stop()
