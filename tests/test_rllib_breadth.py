"""RLlib breadth: APPO, offline IO + off-policy estimators, multi-agent
envs (reference: rllib/algorithms/appo, rllib/offline + estimators,
rllib/env/multi_agent_env.py)."""

import numpy as np
import pytest


def test_appo_iteration_and_improvement(rt_shared):
    from ray_tpu.rllib import APPOConfig

    algo = (APPOConfig()
            .environment("FastCartPole")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=32)
            .training(lr=5e-3, num_batches_per_iter=4)
            .build())
    try:
        r1 = algo.train()
        assert r1["timesteps_this_iter"] > 0
        assert np.isfinite(r1["loss"])
        for _ in range(4):
            r = algo.train()
        assert np.isfinite(r["loss"])
    finally:
        algo.stop()


def test_offline_json_roundtrip_and_estimators(tmp_path):
    from ray_tpu.rllib import (
        ImportanceSampling,
        JsonReader,
        JsonWriter,
        SampleBatch,
        WeightedImportanceSampling,
    )
    from ray_tpu.rllib.sample_batch import (
        ACTIONS,
        DONES,
        LOGPS,
        OBS,
        REWARDS,
    )

    rng = np.random.default_rng(0)
    T = 30
    batch = SampleBatch({
        OBS: rng.normal(size=(T, 4)).astype(np.float32),
        ACTIONS: rng.integers(0, 2, T).astype(np.int32),
        REWARDS: np.ones(T, np.float32),
        DONES: np.asarray([(t % 10) == 9 for t in range(T)]),
        LOGPS: np.full(T, np.log(0.5), np.float32),  # uniform behavior
    })
    writer = JsonWriter(str(tmp_path / "out"))
    writer.write(batch)
    writer.close()
    back = JsonReader(str(tmp_path / "out")).read_all()
    np.testing.assert_allclose(back[OBS], batch[OBS], rtol=1e-6)
    assert back[ACTIONS].dtype == np.int32

    # Target policy == behavior policy -> IS and WIS both estimate the
    # behavior return exactly (all importance weights are 1).
    same = lambda obs, acts: np.full(len(acts), np.log(0.5))
    for cls in (ImportanceSampling, WeightedImportanceSampling):
        est = cls(same, gamma=0.9).estimate(back)
        np.testing.assert_allclose(est["v_target"], est["v_behavior"],
                                   rtol=1e-6)

    # A target policy MORE likely to take the logged actions scores
    # higher under ordinary IS (weights > 1 on every step).
    better = lambda obs, acts: np.full(len(acts), np.log(0.8))
    est = ImportanceSampling(better, gamma=0.9).estimate(back)
    assert est["v_target"] > est["v_behavior"]
    # WIS normalizes the uniform-weight inflation away entirely.
    wis = WeightedImportanceSampling(better, gamma=0.9).estimate(back)
    np.testing.assert_allclose(wis["v_target"], wis["v_behavior"],
                               rtol=1e-6)


class _TwoArmBandit:
    """1-step env: action 1 pays 1.0, action 0 pays 0."""

    def reset(self, seed=None):
        return np.zeros(2, np.float32)

    def step(self, action):
        return np.zeros(2, np.float32), float(action == 1), True, {}


def test_multi_agent_env_and_sampling():
    from ray_tpu.rllib import make_multi_agent, sample_multi_agent
    from ray_tpu.rllib.sample_batch import ACTIONS, OBS, REWARDS

    env_cls = make_multi_agent(_TwoArmBandit, num_agents=4)
    env = env_cls()
    obs = env.reset(seed=0)
    assert set(obs) == {f"agent_{i}" for i in range(4)}

    class _FixedPolicy:
        def __init__(self, action):
            self._a = action

        def compute_actions(self, obs_batch, deterministic=False):
            n = len(obs_batch)
            return (np.full(n, self._a, np.int32),
                    np.zeros(n, np.float32), np.zeros(n, np.float32))

    policies = {"good": _FixedPolicy(1), "bad": _FixedPolicy(0)}

    def mapping(aid):
        return "good" if aid in ("agent_0", "agent_1") else "bad"

    batches = sample_multi_agent(env_cls(), policies, mapping,
                                 num_steps=6)
    assert set(batches) == {"good", "bad"}
    # 2 agents x 6 episodes (1-step env, auto-reset) per policy.
    assert batches["good"][OBS].shape[0] == 12
    assert float(batches["good"][REWARDS].sum()) == 12.0
    assert float(batches["bad"][REWARDS].sum()) == 0.0
    assert set(np.unique(batches["good"][ACTIONS])) == {1}
