"""Preemption-tolerant training: a Trainer survives SIGKILL of the node
daemon hosting its worker mid-run and resumes from the last async
checkpoint (SURVEY §7.3's beyond-reference goal — the TPU-spot story).

Flow: train worker pinned (custom resource) to a daemon-backed node; the
daemon process is SIGKILLed after checkpoints land (a real host crash:
the driver notices via connection EOF); the Trainer's failure loop
respawns the gang, which schedules onto a replacement node and resumes
from the checkpoint. Deterministic training makes the final loss
EXACTLY match an uninterrupted run.
"""

import os
import threading
import time


def _make_train_fn():
    """Closure (not module-level) so cloudpickle ships it BY VALUE:
    workers on remote daemon nodes cannot import pytest test modules."""

    def train_fn(config):
        import time as _time

        from ray_tpu.train import session
        from ray_tpu.train.checkpoint import Checkpoint

        ckpt = session.get_checkpoint()
        start, w = 0, 0.0
        if ckpt is not None:
            d = ckpt.to_dict()
            start, w = d["step"] + 1, d["w"]
        for i in range(start, config["steps"]):
            w = w - config["lr"] * 2.0 * (w - 3.0)  # GD on (w-3)^2
            session.report({"loss": (w - 3.0) ** 2, "step": i, "w": w},
                           Checkpoint.from_dict({"step": i, "w": w}))
            _time.sleep(config["step_time"])

    return train_fn


def _expected_final_w(steps: int, lr: float) -> float:
    w = 0.0
    for _ in range(steps):
        w = w - lr * 2.0 * (w - 3.0)
    return w


def test_trainer_survives_daemon_sigkill(rt_cluster, tmp_path):
    from ray_tpu.train.config import (
        CheckpointConfig,
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.trainer import DataParallelTrainer

    cluster = rt_cluster
    node_a = cluster.add_node(num_cpus=2, resources={"train_slot": 1},
                              remote=True)
    cluster.wait_for_nodes()

    steps, lr = 30, 0.1
    trainer = DataParallelTrainer(
        _make_train_fn(),
        train_loop_config={"steps": steps, "lr": lr, "step_time": 0.25},
        scaling_config=ScalingConfig(
            num_workers=1,
            resources_per_worker={"CPU": 1.0, "train_slot": 1.0}),
        run_config=RunConfig(
            name="preempt", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(async_save=True)),
    )

    result_box = {}

    def run_fit():
        result_box["result"] = trainer.fit()

    t = threading.Thread(target=run_fit)
    t.start()

    # Wait until async checkpoints have landed on disk, then SIGKILL the
    # daemon hosting the train worker mid-run.
    ckpt_dir = os.path.join(str(tmp_path), "preempt", "checkpoints")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if os.path.isdir(ckpt_dir) and len(
                [d for d in os.listdir(ckpt_dir)
                 if d.startswith("checkpoint_")]) >= 3:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("no checkpoints landed before deadline")
    assert t.is_alive(), "training finished before it could be preempted"

    node = cluster.runtime.scheduler.get_node(node_a)
    assert node is not None and getattr(node, "is_remote", False)
    node.process.kill()  # SIGKILL — a spot preemption

    # Replacement capacity arrives (as a spot pool would backfill).
    time.sleep(1.0)
    cluster.add_node(num_cpus=2, resources={"train_slot": 1}, remote=True)

    t.join(timeout=240)
    assert not t.is_alive(), "trainer did not finish after preemption"
    result = result_box["result"]
    assert result.ok, f"trainer failed: {result.error}"

    # The run resumed (did not restart from scratch): some step indices
    # at the front are NOT re-reported after the resume...
    reported_steps = [m["step"] for m in result.metrics_history]
    assert max(reported_steps) == steps - 1
    # ...and the deterministic trajectory converges to EXACTLY the
    # uninterrupted run's final weight.
    expected_w = _expected_final_w(steps, lr)
    assert abs(result.metrics["w"] - expected_w) < 1e-12, (
        f"final w {result.metrics['w']} != uninterrupted {expected_w}")
    assert abs(result.metrics["loss"] - (expected_w - 3.0) ** 2) < 1e-12
    # The preemption actually interrupted mid-run: the full history has
    # more reports than steps (resumed steps re-reported) OR the kill
    # window shows in duplicated step ids.
    assert len(reported_steps) >= steps, (
        "history shorter than steps — did the kill land mid-run?")
