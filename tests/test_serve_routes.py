"""Serve routing hardening: TTL'd route table + router pick logic."""

import http.client
import json
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve._internal import Router

PORT = 18245


@pytest.fixture()
def serve_http(rt_shared):
    serve.start(http_port=PORT)
    yield
    serve.shutdown()


def _get(path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", PORT, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"null")
    finally:
        conn.close()


def test_ttl_route_table_picks_up_longer_prefix(serve_http):
    """Satellite regression test for the f09bf6e TTL'd route table: a
    newly-deployed LONGER route prefix must stop being shadowed by a
    cached shorter match within the TTL."""

    @serve.deployment(name="api_root", route_prefix="/api")
    def api_root(payload=None):
        return "root"

    serve.run(api_root.bind())
    # Cache the route table with /api/sub resolving to the SHORT prefix.
    status, body = _get("/api/sub")
    assert status == 200 and body == "root"

    @serve.deployment(name="api_sub", route_prefix="/api/sub")
    def api_sub(payload=None):
        return "sub"

    serve.run(api_sub.bind())
    proxy = serve.api._state["http_server"]
    ttl = proxy._routes_ttl_s
    # Within one TTL (plus replica-startup slack) the longer prefix
    # must win; poll until the flip, then bound the elapsed time.
    deadline = time.monotonic() + ttl + 20
    t0 = time.monotonic()
    flipped_at = None
    while time.monotonic() < deadline:
        status, body = _get("/api/sub")
        assert status == 200
        if body == "sub":
            flipped_at = time.monotonic() - t0
            break
        time.sleep(0.2)
    assert flipped_at is not None, "longer prefix never took over"
    # The shorter prefix keeps serving its own tree.
    status, body = _get("/api/other")
    assert status == 200 and body == "root"
    status, body = _get("/api")
    assert status == 200 and body == "root"


class _FakeActorID:
    def __init__(self, b: bytes):
        self._b = b

    def binary(self) -> bytes:
        return self._b


class _FakeReplica:
    def __init__(self, i: int):
        self._actor_id = _FakeActorID(bytes([i]) * 20)


def _bare_router(n_replicas: int, max_cq: int = 100,
                 slack: int = 16) -> Router:
    """Router with fields filled in by hand: pick logic only, no
    controller/listener."""
    import threading

    r = Router.__new__(Router)
    r._controller = None
    r._name = "fake"
    r._max_cq = max_cq
    r._version = 0
    r._rr = 0
    r._slack = slack
    r._inflight = {}
    r._nq = 0
    r._metrics = None  # pick-logic tests: no gauge wiring
    r._waiters = 0
    r._lock = threading.Lock()
    r._slot_free = threading.Condition(r._lock)
    r._replicas = [_FakeReplica(i) for i in range(n_replicas)]
    r._keys = [rep._actor_id.binary() for rep in r._replicas]
    return r


class TestPickSlot:
    def test_sticky_fast_path_stays_on_hot_replica(self):
        r = _bare_router(8)
        picks = set()
        with r._slot_free:
            for _ in range(16):  # within slack: all O(1) sticky picks
                replica, key = r._pick_slot_locked()
                picks.add(key)
        assert len(picks) == 1
        assert r._inflight[next(iter(picks))] == 16

    def test_spills_beyond_slack_to_least_loaded(self):
        r = _bare_router(4, slack=4)
        with r._slot_free:
            for _ in range(5):
                r._pick_slot_locked()
            # Sticky is now at load 5 > slack vs best 0: must spill.
            replica, key = r._pick_slot_locked()
        assert key != r._keys[0]
        assert r._inflight[key] == 1

    def test_none_when_all_at_capacity(self):
        r = _bare_router(2, max_cq=3, slack=100)
        with r._slot_free:
            for _ in range(6):
                assert r._pick_slot_locked() is not None
            assert r._pick_slot_locked() is None

    def test_release_reopens_capacity(self):
        r = _bare_router(1, max_cq=2)
        with r._slot_free:
            _, key = r._pick_slot_locked()
            r._pick_slot_locked()
            assert r._pick_slot_locked() is None
        r._release(key)
        with r._slot_free:
            assert r._pick_slot_locked() is not None

    def test_empty_replica_set(self):
        r = _bare_router(0)
        with r._slot_free:
            assert r._pick_slot_locked() is None

    def test_spread_under_saturation(self):
        """Sustained load beyond one replica's slack spreads by load —
        replica-linear behavior, no starvation of the tail replicas."""
        r = _bare_router(4, slack=2)
        with r._slot_free:
            for _ in range(12):
                r._pick_slot_locked()
        loads = sorted(r._inflight.get(k, 0) for k in r._keys)
        assert sum(loads) == 12
        # No replica hoards more than slack above the minimum once the
        # spill regime engages.
        assert loads[-1] - loads[0] <= r._slack + 1
