"""Actor tests: creation, ordered methods, named actors, FT.

Mirrors reference coverage in ``python/ray/tests/test_actor*.py``.
"""

import time

import pytest


def test_actor_basic(rt_shared):
    rt = rt_shared

    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.incr.remote()) == 11
    assert rt.get(c.incr.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_method_ordering(rt_shared):
    rt = rt_shared

    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return len(self.items)

        def get_items(self):
            return self.items

    a = Appender.remote()
    for i in range(20):
        a.add.remote(i)
    assert rt.get(a.get_items.remote()) == list(range(20))


def test_actor_state_isolated(rt_shared):
    rt = rt_shared

    @rt.remote
    class Holder:
        def __init__(self, v):
            self.v = v

        def get(self):
            return self.v

    a, b = Holder.remote("a"), Holder.remote("b")
    assert rt.get([a.get.remote(), b.get.remote()]) == ["a", "b"]


def test_actor_error_in_method(rt_shared):
    rt = rt_shared

    @rt.remote
    class Fragile:
        def boom(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "still alive"

    f = Fragile.remote()
    with pytest.raises(Exception, match="actor method failed"):
        rt.get(f.boom.remote())
    # Method errors don't kill the actor.
    assert rt.get(f.ok.remote()) == "still alive"


def test_actor_constructor_error(rt_shared):
    rt = rt_shared

    @rt.remote
    class Broken:
        def __init__(self):
            raise ValueError("ctor failed")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(Exception):
        rt.get(b.m.remote(), timeout=10)


def test_named_actor(rt_shared):
    rt = rt_shared

    @rt.remote
    class Registry:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Registry.options(name="registry-test").remote()
    h = rt.get_actor("registry-test")
    rt.get(h.set.remote("x", 42))
    assert rt.get(h.get.remote("x")) == 42


def test_actor_handle_passed_to_task(rt_shared):
    rt = rt_shared

    @rt.remote
    class Store:
        def __init__(self):
            self.v = 0

        def set(self, v):
            self.v = v
            return "set"

        def get(self):
            return self.v

    @rt.remote
    def writer(handle, v):
        import ray_tpu as rt2

        return rt2.get(handle.set.remote(v))

    s = Store.remote()
    assert rt.get(writer.remote(s, 99)) == "set"
    assert rt.get(s.get.remote()) == 99


def test_max_concurrency(rt_shared):
    rt = rt_shared

    @rt.remote(max_concurrency=4)
    class Parallel:
        def block(self, t):
            time.sleep(t)
            return "done"

    p = Parallel.remote()
    rt.get(p.block.remote(0.01))  # wait for creation before timing
    t0 = time.time()
    refs = [p.block.remote(0.5) for _ in range(4)]
    rt.get(refs)
    elapsed = time.time() - t0
    # 4 concurrent 0.5s calls should take ~0.5s, not 2s.
    assert elapsed < 1.8, f"max_concurrency not concurrent: {elapsed}"
