"""Actor tests: creation, ordered methods, named actors, FT.

Mirrors reference coverage in ``python/ray/tests/test_actor*.py``.
"""

import time

import pytest


def test_actor_basic(rt_shared):
    rt = rt_shared

    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.incr.remote()) == 11
    assert rt.get(c.incr.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_method_ordering(rt_shared):
    rt = rt_shared

    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return len(self.items)

        def get_items(self):
            return self.items

    a = Appender.remote()
    for i in range(20):
        a.add.remote(i)
    assert rt.get(a.get_items.remote()) == list(range(20))


def test_actor_state_isolated(rt_shared):
    rt = rt_shared

    @rt.remote
    class Holder:
        def __init__(self, v):
            self.v = v

        def get(self):
            return self.v

    a, b = Holder.remote("a"), Holder.remote("b")
    assert rt.get([a.get.remote(), b.get.remote()]) == ["a", "b"]


def test_actor_error_in_method(rt_shared):
    rt = rt_shared

    @rt.remote
    class Fragile:
        def boom(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "still alive"

    f = Fragile.remote()
    with pytest.raises(Exception, match="actor method failed"):
        rt.get(f.boom.remote())
    # Method errors don't kill the actor.
    assert rt.get(f.ok.remote()) == "still alive"


def test_actor_constructor_error(rt_shared):
    rt = rt_shared

    @rt.remote
    class Broken:
        def __init__(self):
            raise ValueError("ctor failed")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(Exception):
        rt.get(b.m.remote(), timeout=10)


def test_named_actor(rt_shared):
    rt = rt_shared

    @rt.remote
    class Registry:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Registry.options(name="registry-test").remote()
    h = rt.get_actor("registry-test")
    rt.get(h.set.remote("x", 42))
    assert rt.get(h.get.remote("x")) == 42


def test_actor_handle_passed_to_task(rt_shared):
    rt = rt_shared

    @rt.remote
    class Store:
        def __init__(self):
            self.v = 0

        def set(self, v):
            self.v = v
            return "set"

        def get(self):
            return self.v

    @rt.remote
    def writer(handle, v):
        import ray_tpu as rt2

        return rt2.get(handle.set.remote(v))

    s = Store.remote()
    assert rt.get(writer.remote(s, 99)) == "set"
    assert rt.get(s.get.remote()) == 99


def test_max_concurrency(rt_shared):
    rt = rt_shared

    @rt.remote(max_concurrency=4)
    class Parallel:
        def block(self, t):
            time.sleep(t)
            return "done"

    p = Parallel.remote()
    rt.get(p.block.remote(0.01))  # wait for creation before timing
    t0 = time.time()
    refs = [p.block.remote(0.5) for _ in range(4)]
    rt.get(refs)
    elapsed = time.time() - t0
    # 4 concurrent 0.5s calls should take ~0.5s, not 2s.
    assert elapsed < 1.8, f"max_concurrency not concurrent: {elapsed}"


def test_async_actor_interleaves_awaits(rt_init):
    """Concurrent coroutine calls share ONE persistent event loop and
    interleave at awaits (reference: per-actor asyncio loop, fiber.h —
    round 1 ran each coroutine on a throwaway loop, serializing them)."""
    import time as _time

    import ray_tpu as rt

    @rt.remote
    class AsyncGather:
        def __init__(self):
            self.events = []

        async def slow_echo(self, tag, delay):
            import asyncio

            self.events.append(("start", tag))
            await asyncio.sleep(delay)
            self.events.append(("end", tag))
            return tag

        async def get_events(self):
            return list(self.events)

    a = AsyncGather.remote()
    # Warm up: wait for actor construction + first-call import costs so the
    # timed window below measures interleaving, not cold-start.
    assert rt.get(a.get_events.remote(), timeout=60) == []
    t0 = _time.monotonic()
    out = rt.get([a.slow_echo.remote(i, 0.4) for i in range(5)], timeout=30)
    elapsed = _time.monotonic() - t0
    assert out == list(range(5))
    # interleaved: 5 x 0.4s sleeps overlap (serial would be >= 2.0s)
    assert elapsed < 1.6, f"awaits did not interleave ({elapsed:.2f}s)"
    events = rt.get(a.get_events.remote(), timeout=10)
    starts_before_first_end = [e for e in events[:5] if e[0] == "start"]
    assert len(starts_before_first_end) >= 2  # overlapping lifetimes


def test_concurrency_groups_cap_and_order(rt_init):
    """Methods in a named concurrency group run under that group's own
    cap while other groups proceed (reference:
    concurrency_group_manager.h)."""
    import time as _time

    import ray_tpu as rt

    @rt.remote(concurrency_groups={"io": 2, "compute": 1})
    class Grouped:
        def __init__(self):
            import threading

            self.lock = threading.Lock()
            self.peak_io = 0
            self.cur_io = 0
            self.compute_order = []

        @rt.method(concurrency_group="io")
        def io_call(self, i):
            with self.lock:
                self.cur_io += 1
                self.peak_io = max(self.peak_io, self.cur_io)
            _time.sleep(0.1)
            with self.lock:
                self.cur_io -= 1
            return i

        @rt.method(concurrency_group="compute")
        def compute_call(self, i):
            self.compute_order.append(i)
            return i

        def stats(self):
            return {"peak_io": self.peak_io, "order": self.compute_order}

    g = Grouped.remote()
    refs = [g.io_call.remote(i) for i in range(6)]
    refs += [g.compute_call.remote(i) for i in range(4)]
    rt.get(refs, timeout=30)
    stats = rt.get(g.stats.remote(), timeout=10)
    assert stats["peak_io"] <= 2, stats  # io cap enforced
    assert stats["order"] == [0, 1, 2, 3]  # compute group is FIFO-ordered


def test_actor_ready_fast_with_warm_pool(rt_init):
    """Actor creation claims a prestarted idle worker instead of forking a
    fresh process (reference: ``worker_pool.h:104`` PopWorker serves
    actor-creation tasks) — actor-ready latency must be well under a cold
    spawn + jax import (~10s)."""
    import time as _time

    import ray_tpu as rt

    # Warm the pool: ensure at least one worker is spawned + registered.
    @rt.remote
    def _noop():
        return None

    rt.get([_noop.remote() for _ in range(2)], timeout=60)

    @rt.remote
    class Echo:
        def ping(self):
            return "pong"

    t0 = _time.monotonic()
    a = Echo.remote()
    assert rt.get(a.ping.remote(), timeout=10) == "pong"
    elapsed = _time.monotonic() - t0
    assert elapsed < 1.0, f"actor cold-start too slow ({elapsed:.2f}s)"
