"""Hot-path invariants: copy counts, frame alignment, pinned eviction.

These tests PIN the data-plane profile this round's optimization
campaign established, so a future refactor that silently adds a copy or
breaks zero-copy reads fails loudly instead of showing up as a bench
regression two rounds later.
"""

import gc

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.ids import NodeID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.core.serialization import Serializer, _align64
from ray_tpu.observability import hotpath

BIG = 10 * 1024 * 1024


def _oid(i: int) -> ObjectID:
    return ObjectID.for_put(TaskID.nil(), i)


class TestCopyCounts:
    def test_put_large_is_one_copy_and_get_is_zero(self, rt_shared):
        big = np.zeros(BIG // 8, dtype=np.float64)
        rt.put(big)  # prime the path
        hotpath.reset("copy.")
        ref = rt.put(big)
        copies = hotpath.breakdown("copy.")
        assert copies.get("copy.serialize.write_into", 0) == 1, copies
        assert copies.get("copy.serialize.to_bytes", 0) == 0, copies
        hotpath.reset("copy.")
        got = rt.get(ref)
        assert got.nbytes == big.nbytes
        copies = hotpath.breakdown("copy.")
        assert copies.get("copy.store.read_bytes", 0) == 0, copies
        del got
        gc.collect()

    def test_get_large_returns_readonly_view(self, rt_shared):
        big = np.arange(BIG // 8, dtype=np.float64)
        got = rt.get(rt.put(big))
        assert (got[:64] == big[:64]).all()
        # Zero-copy means the array must not be writable (it aliases
        # the sealed arena extent).
        assert not got.flags.writeable
        del got
        gc.collect()


class TestFrameAlignment:
    def test_out_of_band_buffers_are_64b_aligned(self):
        ser = Serializer(ref_class=ObjectRef)
        payload = {"a": np.arange(17, dtype=np.int32),
                   "b": np.zeros(1000), "c": b"x" * 100}
        so = ser.serialize(payload)
        frame = so.to_bytes()
        assert len(frame) == so.frame_bytes()
        n = int.from_bytes(frame[:4], "little")
        assert n == 1 + len(so.buffers) and len(so.buffers) >= 2
        sizes = [int.from_bytes(frame[4 + 8 * i:12 + 8 * i], "little")
                 for i in range(n)]
        off = 4 + 8 * n + sizes[0]
        for s in sizes[1:]:
            off = _align64(off)
            assert off % 64 == 0
            off += s
        assert off == len(frame)
        # round-trips through the padded layout
        back = ser.deserialize(memoryview(frame))
        assert (back["a"] == payload["a"]).all()
        assert (back["b"] == payload["b"]).all()
        assert back["c"] == payload["c"]

    def test_native_put_frame_matches_python_writer(self):
        native = pytest.importorskip("ray_tpu._native")
        if not native.available():
            pytest.skip("native store unavailable")
        ser = Serializer(ref_class=ObjectRef)
        store = native.NativeStore.create("/rt_test_pf_parity", 32 << 20)
        try:
            for i, payload in enumerate((
                    np.arange(4096, dtype=np.float32),
                    {"w": np.ones((8, 8)), "meta": [1, 2, 3]},
                    b"z" * 200_000)):
                so = ser.serialize(payload)
                key = bytes([i]) * 20
                store.put_frame(key, so.inband, so.buffers)
                view = store.get_pinned(key)
                # Byte-for-byte parity: C-side offset math == python
                # writer == frame_bytes (sealed size is the view size).
                assert view.nbytes == so.frame_bytes()
                assert bytes(view) == so.to_bytes()
                del view
                gc.collect()
        finally:
            store.close(unlink=True)


class TestPinnedEviction:
    """Satellite: eviction with an exported zero-copy view defers the
    extent free until the view is released, and a put into a full arena
    still succeeds (spill/retry), never serving torn data."""

    def _store(self, capacity: int) -> SharedMemoryStore:
        return SharedMemoryStore(NodeID.from_random(), capacity=capacity)

    def test_delete_with_pinned_view_defers_free(self):
        native = pytest.importorskip("ray_tpu._native")
        if not native.available():
            pytest.skip("native store unavailable")
        ser = Serializer(ref_class=ObjectRef)
        store = self._store(capacity=64 * 1024 * 1024)
        if store._arena is None:
            store.destroy()
            pytest.skip("arena backend unavailable")
        try:
            a = np.full(20 * 1024 * 1024 // 8, 7.0)
            oid_a = _oid(1)
            store.put_serialized(oid_a, ser.serialize(a))
            view = store.get_pinned(oid_a)
            arr = np.asarray(ser.deserialize(view))
            del view
            gc.collect()
            store.delete(oid_a)  # deferred: arr still pins the extent
            assert not store.contains(oid_a)
            assert (arr[:1024] == 7.0).all()  # extent not reused

            # Fill the arena past what logical accounting thinks is
            # free (the pinned extent is invisible to it): the put must
            # still succeed via the spill/retry path.
            for i in range(2, 5):
                store.put_serialized(
                    _oid(i), ser.serialize(np.full(
                        20 * 1024 * 1024 // 8, float(i))))
            # The pinned bytes survived every allocation above.
            assert (arr[:1024] == 7.0).all()
            assert (arr[-1024:] == 7.0).all()
            del arr
            gc.collect()  # releases the pin -> extent truly freed
            # All three later puts remain tracked (some may have
            # spilled to make room); the deleted object is gone.
            assert store.stats()["num_objects"] == 3
            assert not store.contains(oid_a)
        finally:
            store.destroy()

    def test_put_after_release_reuses_freed_extent(self):
        native = pytest.importorskip("ray_tpu._native")
        if not native.available():
            pytest.skip("native store unavailable")
        ser = Serializer(ref_class=ObjectRef)
        store = self._store(capacity=48 * 1024 * 1024)
        if store._arena is None:
            store.destroy()
            pytest.skip("arena backend unavailable")
        try:
            a = np.full(30 * 1024 * 1024 // 8, 1.0)
            oid_a = _oid(11)
            store.put_serialized(oid_a, ser.serialize(a))
            view = store.get_pinned(oid_a)
            pinned = np.asarray(ser.deserialize(view))
            del view
            gc.collect()
            store.delete(oid_a)
            # A 30MB put cannot fit while 30MB is pinned in a 48MB
            # arena and nothing is spillable — after the pin drops, the
            # same put succeeds in the recycled extent.
            del pinned
            gc.collect()
            oid_b = _oid(12)
            store.put_serialized(oid_b, ser.serialize(a * 2))
            got = ser.deserialize(store.get_pinned(oid_b))
            assert float(got[0]) == 2.0
            del got
            gc.collect()
        finally:
            store.destroy()
