"""Model tests: shapes, loss decrease, llama decode-vs-forward parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _same_structure(params, axes):
    """Axes leaves are tuples (pytree nodes), so compare with is_leaf."""
    s1 = jax.tree.structure(params)
    s2 = jax.tree.structure(
        axes, is_leaf=lambda x: isinstance(x, tuple)
    )
    return s1 == s2


def test_gpt2_forward_shapes():
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config(vocab_size=128, max_seq=32, num_layers=2,
                          num_heads=2, d_model=32, dtype=jnp.float32,
                          attention_impl="reference")
    params, axes = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    assert _same_structure(params, axes)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = gpt2.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, 128)


def test_resnet_cifar_train_step():
    from ray_tpu.models import resnet

    cfg = resnet.ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=10,
                              dtype=jnp.float32)
    params, stats = resnet.init_params(jax.random.PRNGKey(0), cfg)
    images = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    labels = jnp.array([0, 1, 2, 3])
    batch = {"image": images, "label": labels}

    import optax

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, stats, opt_state):
        (loss, (new_stats, acc)), grads = jax.value_and_grad(
            resnet.loss_fn, has_aux=True)(params, stats, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    losses = []
    for _ in range(6):
        params, stats, opt_state, loss = step(params, stats, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vit_forward_and_loss():
    from ray_tpu.models import vit

    cfg = vit.ViTConfig(image_size=32, patch_size=8, num_layers=2,
                        num_heads=2, d_model=32, d_mlp=64, num_classes=10,
                        dtype=jnp.float32, remat=False)
    params, axes = vit.init_params(jax.random.PRNGKey(0), cfg)
    assert _same_structure(params, axes)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits = vit.forward(params, images, cfg)
    assert logits.shape == (2, 10)
    loss = vit.loss_fn(params, {"image": images,
                                "label": jnp.array([1, 2])}, cfg)
    assert np.isfinite(float(loss))


def test_llama_forward_and_loss():
    from ray_tpu.models import llama

    cfg = llama.CONFIGS["llama-tiny"]
    params, axes = llama.init_params(jax.random.PRNGKey(0), cfg)
    assert _same_structure(params, axes)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                cfg.vocab_size)
    loss = llama.loss_fn(params, {"tokens": tokens}, cfg)
    assert np.isfinite(float(loss))


def test_llama_decode_matches_forward():
    """KV-cache decode logits must match full-forward logits."""
    from ray_tpu.models import llama

    cfg = llama.CONFIGS["llama-tiny"]
    params, _ = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0,
                                cfg.vocab_size)
    full = llama.forward(params, tokens, cfg)  # [1, 8, V]

    cache = llama.init_kv_cache(cfg, 1)
    step_logits = []
    for i in range(8):
        logits, cache = llama.decode_step(params, cache, tokens[:, i],
                                          jnp.asarray(i), cfg)
        step_logits.append(logits)
    stepwise = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stepwise),
                               atol=2e-3, rtol=2e-3)


def test_llama_generate():
    from ray_tpu.models import llama

    cfg = llama.CONFIGS["llama-tiny"]
    params, _ = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                cfg.vocab_size)
    out = llama.generate(params, prompt, cfg, max_new=5)
    assert out.shape == (2, 9)
